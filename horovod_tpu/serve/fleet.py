"""Fault-tolerant multi-replica serving: N engines behind one router.

PR 9 made training survive real clusters (classified worker exits,
heartbeat watchdog, budgeted relaunches); this module gives serving the
same story instead of reinventing it. A :class:`ServeFleet` runs N
:class:`~horovod_tpu.serve.engine.ServeEngine` replicas behind a
least-loaded router (:mod:`~horovod_tpu.serve.router`), and every
failure mode is first-class:

* **replica death** (``kill:`` faults, real crashes) is drained and
  **redispatched**: the router — which streamed every emitted token to
  the client and therefore knows each request's generated-so-far
  prefix — re-submits unfinished requests to survivors with the prefix
  folded into the prompt (:func:`~horovod_tpu.serve.scheduler.
  rebase_for_recompute`, the same arithmetic as eviction-recompute).
  Tokens already emitted are NEVER re-emitted (at-most-once), and
  greedy output stays bit-identical to an uninterrupted run (pinned in
  tests/test_serve_fleet.py and the ``serve_bench --fleet`` A/B);
* **silent stalls** become classified incidents: every live replica's
  per-replica heartbeat file is stamped at the END of each fleet tick
  (all together, once every replica has stepped — see :meth:`ServeFleet.
  step` for why per-step stamping would mis-kill healthy peers), and a
  :class:`~horovod_tpu.elastic.supervisor.HealthWatchdog` (PR 9's, not
  a copy) kills any replica stale past the timeout — classified
  ``stalled`` via :class:`~horovod_tpu.run.driver.WorkerExit`, exactly
  the training taxonomy;
* **relaunch** consumes a fleet-wide restart budget with exponential
  backoff (the anti-pattern of an unbudgeted, backoff-less retry loop
  is lint rule HVD010); a replica past the budget is ``failed`` and the
  fleet degrades;
* a degraded fleet **sheds load** instead of letting TTFT diverge: the
  router's admission queue is bounded (``FleetConfig.max_queue``), and
  overflow is rejected terminally — ``reject_reason="overloaded"``
  with a ``retry_after`` hint — while requests that can NEVER fit the
  replica geometry reject as ``infeasible``. Rejected requests never
  touch a replica, so they can never allocate KV pages (allocator
  conservation is pinned in tests).

Replicas come in two placements (``FleetConfig.transport``):

* ``inproc`` (default): engines in the router's process with a
  process-shaped lifecycle (real heartbeat files, the real watchdog,
  the real exit taxonomy with synthetic ``-SIGKILL`` codes) — the CI
  fast lane: the whole recovery story, including the bit-exact
  redispatch pin, exercisable on CPU in seconds with deterministic
  fault injection and an injectable clock;
* ``process``: each replica is its own ``python -m
  horovod_tpu.serve.worker`` OS process (spawned/reaped through the
  PR-9 :mod:`horovod_tpu.run` machinery) behind the deadline-checked
  framed RPC transport (:mod:`~horovod_tpu.serve.transport`) — REAL
  crash isolation. ``kill:`` faults become genuine
  ``os.kill(pid, SIGKILL)``; a ``stall:`` fault genuinely wedges the
  worker's engine thread so only the stale heartbeat (the worker
  stamps its own file per served tick) and the
  :class:`~horovod_tpu.elastic.supervisor.HealthWatchdog` catch it;
  and ANY transport failure — connection refused, a frame torn by a
  mid-write death, a checksum mismatch, a deadline expiry — is
  converted into this same replica-death path, never retried at the
  RPC layer (a blind resend could double-apply a submit and break
  at-most-once);
* ``tcp``: the same frame protocol over TCP with a shared-secret
  connect handshake, placed across HOSTS (``FleetConfig.hosts``,
  round-robin; remote hosts over ssh with the launcher's pty-HUP kill
  discipline). A machine is then a first-class failure domain: a lost
  host — ``kill:host=`` fault, NIC ``partition:``, ssh HUP — drains
  and redispatches ALL its replicas in one classified ``host_down``
  incident (a transport death triggers a short probe sweep of the
  host's other replicas to coalesce the loss), and stall liveness
  rides the transport itself (a heartbeat sequence in every
  step/ping/collect reply, aged by the router's clock) because a
  remote heartbeat file is invisible to the router's watchdog. Every
  connection to a host routes through one shared
  :class:`~horovod_tpu.serve.netfault.NetFaults` state, so partitions
  are deterministically injectable on loopback TCP in CI.

Either way the router's drain uses only router-side bookkeeping
(dispatched requests + streamed tokens), never the dead engine's
internals, and a crash loses the replica's engine state wholesale — in
process mode that sentence is literally true of a SIGKILLed address
space.

**Weights travel the wire, versioned** (the round-15 tentpole;
:mod:`~horovod_tpu.serve.params_wire`): every worker incarnation —
spawn, relaunch, redispatch, unix or tcp — receives its ServeConfig
and a content-addressed params artifact over the RPC transport itself
(chunked, per-chunk CRC'd, whole-artifact digest-verified, atomically
committed), so no placement assumes a shared filesystem and every
replica provably decodes with bit-identical weights. The push lane is
the ONE place a transport failure retries (chunk writes are
idempotent): torn/corrupted transfers are classified transfer
incidents that resume from the worker's verified offset under the
budgeted backoff, never a silently wrong model.
:meth:`ServeFleet.update_params` rolls a NEW weights version through
the fleet with zero downtime — drain one replica (peers carry its
traffic) → push → verify digest → readmit — while the router pins
each request's entire decode to one version: redispatch rebases only
onto a same-version replica, and a version no replica can ever serve
again triggers the explicit restart-under-current-version policy — a
mid-stream mix of two models' tokens is impossible by construction.

docs/serving.md "The fleet" / "Process fleet" / "Weight distribution
and rolling updates" cover the runbooks.
"""

from __future__ import annotations

import dataclasses
import os
import signal as _signal
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

from horovod_tpu.elastic.faults import (FaultPlanError, ServeFaultAction,
                                        parse_serve_fault_plan)
from horovod_tpu.elastic.signals import Heartbeat, namespaced_heartbeat_dir
from horovod_tpu.elastic.supervisor import HealthWatchdog
from horovod_tpu.run.driver import WorkerExit
from horovod_tpu.serve import params_wire
from horovod_tpu.serve.config import FleetConfig, ServeConfig
from horovod_tpu.serve.engine import ServeEngine
from horovod_tpu.serve.router import (pick_replica, replica_load,
                                      retry_after_hint)
from horovod_tpu.serve.scheduler import (Request, RequestState,
                                         rebase_for_recompute,
                                         restart_from_scratch)
from horovod_tpu.serve.transport import (ChecksumError, ConnectionLost,
                                         RpcClient, TransportError,
                                         remote_error_kind)


def _log(msg: str) -> None:
    print(f"[hvd fleet] {msg}", file=sys.stderr, flush=True)


class Replica:
    """One engine + its process-shaped lifecycle.

    ``state``: ``healthy`` (serving; may currently be stalled or
    slowed by a fault) -> ``dead`` (killed; relaunch pending behind the
    backoff) -> ``healthy`` again, or ``failed`` (terminal: the restart
    budget is spent). ``assigned`` is the ROUTER's bookkeeping —
    dispatched-but-unfinished requests — and is what drain/redispatch
    reads, never the engine's internals (a crashed engine's state is
    gone).
    """

    #: Which FleetConfig.transport shape this replica is.
    transport = "inproc"
    #: In-process replicas are heartbeat-stamped by the FLEET at the
    #: end of each tick; process workers stamp their own file per
    #: served tick (the fleet must never stamp for them — a wedged
    #: worker would look alive forever).
    stamps_own_heartbeat = False
    #: How stall liveness is observed: ``file`` (heartbeat files + the
    #: PR-9 HealthWatchdog — in-process and same-host process
    #: replicas) or ``transport`` (a heartbeat SEQUENCE riding the
    #: step/ping/collect replies, aged by the ROUTER's clock — TCP
    #: replicas, whose heartbeat file may live on another machine the
    #: router cannot stat).
    liveness = "file"
    #: Host failure-domain index (tcp placement only).
    host: Optional[int] = None
    #: Disaggregated pool ("prefill"/"decode"; None = colocated).
    #: Positional off FleetConfig.pools and IMMUTABLE for the fleet's
    #: lifetime — a relaunched replica keeps its role.
    role: Optional[str] = None

    def __init__(self, rid: int, engine, heartbeat: Optional[Heartbeat]):
        self.id = rid
        self.engine = engine
        self.heartbeat = heartbeat
        self.state = "healthy"
        self.assigned: List[Request] = []
        self.exit: Optional[WorkerExit] = None
        self.restarts = 0               # relaunches consumed so far
        self.relaunch_at: Optional[float] = None
        self.stall_until: Optional[float] = None   # None = not stalled
        self.slow_factor = 1.0
        self.steps = 0
        #: Transport-liveness channel (tcp): last observed heartbeat
        #: sequence value + the ROUTER-clock stamp of when it changed.
        self.hb_seq: Optional[int] = None
        self.hb_at: Optional[float] = None
        #: Params version this replica serves (None = wire-init still
        #: pending: a worker with no weights yet takes no traffic) +
        #: the digest the fleet verified it against.
        self.version: Optional[int] = None
        self.params_sha: Optional[str] = None
        #: False while the rolling update drains this replica — the
        #: router routes around it; its in-flight requests finish.
        self.accepting = True
        #: Armed push-lane fault (the transfer:/corrupt: verbs),
        #: consumed one-shot by the next params push.
        self.push_fault: Optional[str] = None

    @property
    def healthy(self) -> bool:
        return self.state == "healthy"

    def ensure_dead(self, code_hint: int) -> int:
        """Make the replica's failure domain actually dead and return
        the best-evidence exit code. In-process replicas have no OS
        process — the synthetic hint IS the evidence; process replicas
        SIGKILL + reap and return the real code."""
        return code_hint

    def shutdown(self, deadline: float) -> None:
        """Graceful teardown hook for :meth:`ServeFleet.close` (base:
        nothing to tear down — the engine dies with the router)."""

    def adopt(self, fresh: "Replica") -> None:
        """Take over a freshly-spawned incarnation's live half (the
        relaunch path mutates the existing Replica object in place so
        router bookkeeping and per-id metrics keep their identity)."""
        self.engine = fresh.engine
        self.heartbeat = fresh.heartbeat
        self.version = fresh.version
        self.params_sha = fresh.params_sha
        self.accepting = True


class ProcessReplica(Replica):
    """One replica as its own OS process behind the RPC transport.

    ``engine`` is an :class:`_EngineProxy` exposing the exact attribute
    surface the router and fleet read on a live in-process engine
    (free slots, occupancy, queue length, submit, step, the terminal
    lists) — every PR-12 code path runs unchanged; only the transport
    underneath differs. ``proc`` is the worker's ``Popen`` (its own
    process group via :func:`horovod_tpu.run.spawn_worker`)."""

    transport = "process"
    stamps_own_heartbeat = True

    def __init__(self, rid: int, engine: "_EngineProxy",
                 heartbeat: Heartbeat, proc, client: RpcClient,
                 sock_path: str):
        super().__init__(rid, engine, heartbeat)
        self.proc = proc
        self.client = client
        self.sock_path = sock_path

    def _cleanup_ipc(self) -> None:
        if self.client is not None:
            self.client.close()
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass

    def ensure_dead(self, code_hint: int) -> int:
        """Genuine ``SIGKILL`` of the worker's process group + reap (no
        zombies), returning the REAL exit code when reapable: a worker
        that already died of its own fault (the ``kill:`` injection, an
        OOM) reports that code; one we killed reports ``-SIGKILL``."""
        from horovod_tpu.run import kill_worker

        code = kill_worker(self.proc)
        self._cleanup_ipc()
        return code if code is not None else code_hint

    def shutdown(self, deadline: float) -> None:
        """close()'s graceful path: ``shutdown`` RPC under a short
        deadline, then SIGTERM → SIGKILL escalation, then reap — a
        stalled (wedged engine thread) worker still answers the RPC on
        its control thread, and one whose RPC thread is gone too falls
        through to the signals. Either way the process is REAPED."""
        from horovod_tpu.run import terminate_worker

        if self.proc.poll() is None and self.client is not None:
            acked = True
            try:
                self.client.call("shutdown", timeout=deadline)
            except TransportError:
                acked = False   # already burned the deadline: escalate
            if acked:
                try:
                    self.proc.wait(deadline)
                except Exception:   # TimeoutExpired: escalate below
                    pass
        terminate_worker(self.proc)
        self._cleanup_ipc()

    def adopt(self, fresh: "Replica") -> None:
        super().adopt(fresh)
        self.proc = fresh.proc
        self.client = fresh.client
        self.sock_path = fresh.sock_path


class TcpReplica(ProcessReplica):
    """One replica worker behind the TCP frame transport, possibly on
    another HOST (ssh placement). Same RPC surface and failure →
    drain/redispatch rules as :class:`ProcessReplica`; what changes:

    * ``host`` indexes the fleet's host table — the replica's failure
      DOMAIN: a transport failure here makes the fleet probe the
      host's other replicas, and a whole-host loss is one classified
      ``host_down`` incident;
    * liveness rides the transport (the worker's heartbeat-sequence
      counter in every ``step``/``ping``/``collect`` reply, aged by
      the router's clock) because a remote heartbeat FILE is not
      visible to the router's watchdog;
    * for ssh-placed workers ``proc`` is the local ssh CLIENT — its
      process group is the kill handle (SIGKILL → pty HUP kills the
      remote tree), but its exit code is only the worker's when the
      remote exited normally: signal deaths and dead sessions report
      255/-signum, which say nothing about the worker, so
      :meth:`ensure_dead` falls back to the caller's evidence hint.
    """

    transport = "tcp"
    liveness = "transport"
    stamps_own_heartbeat = True   # the fleet never stamps files for it

    def __init__(self, rid: int, engine: "_EngineProxy",
                 proc, client: RpcClient, endpoint: str,
                 host: int, host_name: str, via_ssh: bool):
        super().__init__(rid, engine, None, proc, client, endpoint)
        self.host = host
        self.host_name = host_name
        self.via_ssh = via_ssh

    def _cleanup_ipc(self) -> None:
        if self.client is not None:
            self.client.close()
        # No socket file to unlink: the endpoint is a network address.

    def ensure_dead(self, code_hint: int) -> int:
        from horovod_tpu.run import kill_worker

        code = kill_worker(self.proc)
        self._cleanup_ipc()
        if code is None:
            return code_hint
        if self.via_ssh and (code < 0 or code == 255):
            # The ssh CLIENT's own death (our SIGKILL of it, or ssh's
            # 255 for a signal-killed/unreachable remote) is not the
            # worker's exit code — classify from the caller's evidence.
            return code_hint
        return code


class _SizedQueueView:
    """``len()``-only stand-in for a remote engine's queue (the router
    checks ``len(eng.scheduler.queue)`` for the engine-side bound)."""

    def __init__(self):
        self.n = 0

    def __len__(self) -> int:
        return self.n


class _ProxyCache:
    def __init__(self, fits_fn: Callable[[int, int], bool]):
        self._fits = fits_fn
        self._occ = 0.0

    def occupancy(self) -> float:
        return self._occ

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        return self._fits(prompt_len, max_new_tokens)


class _ProxyScheduler:
    def __init__(self, proxy: "_EngineProxy"):
        self._proxy = proxy
        self.queue = _SizedQueueView()
        self.rejected: List[Request] = []

    def submit(self, req: Request) -> bool:
        return self._proxy.submit(req)


class _EngineProxy:
    """Router-side mirror of one worker's engine.

    State the router reads between polls (free slots, occupancy, queue
    length) is the last ``step`` RPC's snapshot; dispatch-limit
    correctness never depends on it (the in-flight cap is checked
    against ``Replica.assigned``, which is router-owned). Token
    streams are mirrored via ``collect``: the router asks for
    everything past what it has already applied per request
    (``since``), so the mirror — which is what drain/redispatch and
    the at-most-once guarantee read — is exactly the set of tokens the
    router has observed. Latency stamps use the ROUTER's clock at
    collect time: what a streaming client at the router actually
    perceives (worker-side clock stamps never cross the wire, so no
    skew to reconcile).

    Any :class:`TransportError` out of these methods means the replica
    must die; the fleet converts it (``_transport_death``) — the proxy
    itself never retries or masks.
    """

    def __init__(self, client: RpcClient, config: ServeConfig,
                 fits_fn: Callable[[int, int], bool], clock):
        self.client = client
        self.config = config
        self.clock = clock
        self.cache = _ProxyCache(fits_fn)
        self.scheduler = _ProxyScheduler(self)
        self.finished: List[Request] = []
        self.timed_out: List[Request] = []
        self.evicted: List[Request] = []
        self._free = config.decode_slots
        self._in_flight = 0
        self._last_ticks = 0
        #: Worker heartbeat-sequence value last seen in a reply (the
        #: transport liveness channel: the worker bumps it once per
        #: engine-loop iteration, idle ones included, so a frozen
        #: value + work outstanding = a wedged engine thread).
        self.last_hb: Optional[int] = None
        #: rid -> worker-output tokens already applied to the mirror.
        self._streamed: Dict[int, int] = {}
        self._by_rid: Dict[int, Request] = {}
        #: Router rids parked in the worker's handoff bay (last step
        #: RPC's snapshot; always empty outside disaggregated pools).
        self.handoff_rids: List[int] = []
        #: Last step RPC's prefix-cache snapshot (None: caching off,
        #: or a worker — e.g. the protocol stub — that never stamps
        #: it; every consumer tolerates the absence).
        self.last_prefix: Optional[Dict] = None
        #: rid -> (hit_tokens, hit_pages) last seen from THIS worker
        #: incarnation. Worker counters restart at 0 per incarnation
        #: while the router mirror is cumulative across redispatches
        #: (the drain baseline depends on it) — so stamps apply as
        #: deltas, never overwrites.
        self._prefix_seen: Dict[int, tuple] = {}

    def _free_slots(self) -> int:
        return self._free

    def submit(self, req: Request) -> bool:
        now = self.clock()
        r = self.client.call("submit", {
            "rid": req.rid,
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "eos_token": req.eos_token,
            "seed": int(req.seed),
            "age": max(0.0, now - req.arrival),
            "ttl": req.ttl,
            "prefill_only": bool(getattr(req, "prefill_only", False)),
        })
        if r.get("accepted"):
            self._streamed[req.rid] = 0
            self._by_rid[req.rid] = req
            self._prefix_seen[req.rid] = (0, 0)
            req.state = RequestState.QUEUED
            if req.t_admit is None:
                req.t_admit = now
            # Keep the snapshot honest WITHIN a tick: an accepted
            # submit sits in the worker's queue until picked, so a
            # second dispatch this tick must see the occupancy (an
            # engine-side max_queue would otherwise terminally reject
            # a request the router's contract says should WAIT at the
            # fleet head). The next step RPC overwrites with truth.
            self.scheduler.queue.n += 1
            return True
        req.state = RequestState.REJECTED
        req.reject_reason = r.get("reject_reason")
        req.retry_after = r.get("retry_after")
        self.scheduler.rejected.append(req)
        return False

    def step(self) -> bool:
        s = self.client.call("step")
        self._free = int(s["free_slots"])
        self.cache._occ = float(s["occupancy"])
        self.scheduler.queue.n = int(s["queue_len"])
        self._in_flight = int(s["in_flight"])
        if s.get("hb") is not None:
            self.last_hb = int(s["hb"])
        if s.get("prefix") is not None:
            self.last_prefix = s["prefix"]
        self.handoff_rids = [int(x) for x in s.get("handoff") or ()]
        stepped = int(s["ticks"]) > self._last_ticks
        self._last_ticks = int(s["ticks"])
        if not self._by_rid:
            # No router-owned request is outstanding, so no event or
            # progress can exist (rids are born in submit and live in
            # _by_rid until their terminal applies): skip the collect
            # round trip — idle fleets pay one RPC per tick, not two,
            # and rpc_ms isn't flooded with empty collects.
            return stepped
        c = self.client.call("collect", {
            "since": {str(r): n for r, n in self._streamed.items()}})
        if c.get("hb") is not None:
            self.last_hb = int(c["hb"])
        now = self.clock()
        for pr in c.get("progress", ()):
            req = self._by_rid.get(int(pr["rid"]))
            if req is None:
                continue
            self._apply_tokens(req, pr.get("tokens") or [], now)
            req.prefill_pos = int(pr.get("prefill_pos", req.prefill_pos))
            self._apply_prefix(req, pr)
        for ev in c.get("events", ()):
            rid = int(ev["rid"])
            req = self._by_rid.pop(rid, None)
            if req is None:
                continue
            done = self._streamed.pop(rid, 0)
            self._apply_tokens(req, ev.get("output", [])[done:], now)
            req.prefill_pos = int(ev.get("prefill_pos", 0))
            req.evictions = int(ev.get("evictions", req.evictions))
            self._apply_prefix(req, ev)
            self._prefix_seen.pop(rid, None)
            req.state = ev["state"]
            if req.state == RequestState.REJECTED:
                req.reject_reason = ev.get("reject_reason")
                req.retry_after = ev.get("retry_after")
                self.scheduler.rejected.append(req)
            elif req.state == RequestState.TIMEOUT:
                req.t_finish = now
                self.timed_out.append(req)
            elif req.state == RequestState.EVICTED:
                self.evicted.append(req)
            else:
                req.t_finish = now
                self.finished.append(req)
        return stepped

    def _apply_prefix(self, req: Request, payload: Dict) -> None:
        """Fold one progress/terminal payload's prefix stamps into the
        mirror as DELTAS against what this incarnation already
        reported (see ``_prefix_seen``). Payloads without the keys —
        stub workers, pre-prefix workers — apply nothing."""
        if "prefix_hit_tokens" not in payload:
            return
        seen_t, seen_p = self._prefix_seen.get(req.rid, (0, 0))
        wt = int(payload["prefix_hit_tokens"])
        wp = int(payload.get("prefix_hit_pages", seen_p))
        req.prefix_hit_tokens += max(0, wt - seen_t)
        req.prefix_hit_pages += max(0, wp - seen_p)
        self._prefix_seen[req.rid] = (wt, wp)

    def _apply_tokens(self, req: Request, tokens, now: float) -> None:
        if not tokens:
            return
        req.output.extend(int(t) for t in tokens)
        req.generated.extend(int(t) for t in tokens)
        if req.t_first_token is None:
            req.t_first_token = now
        req.token_times.extend([now] * len(tokens))
        if req.rid in self._streamed:
            self._streamed[req.rid] += len(tokens)

    def reset_metrics(self) -> None:
        self.client.call("reset_metrics")
        self._last_ticks = 0
        self.finished = []
        self.timed_out = []
        self.evicted = []
        self.scheduler.rejected = []


class ServeFleet:
    """N continuous-batching replicas behind a fault-tolerant router.

    ``params``/``config`` build each replica's engine (one geometry
    fleet-wide); ``fleet`` sizes the fleet and its recovery policy.
    ``clock`` and ``sleep`` are injectable for deterministic tests —
    the heartbeat/watchdog lane alone reads real file mtimes, so stall
    detection tests run on the wall clock (slow-marked).

    The lifecycle mirrors :class:`ServeEngine`: :meth:`submit` admits
    (or sheds), :meth:`step` runs one fleet tick (faults -> watchdog ->
    relaunches -> dispatch -> one engine step per live replica),
    :meth:`run` drains to idle, :meth:`stats` aggregates SLO + recovery
    metrics.
    """

    def __init__(self, params: Dict, config: ServeConfig,
                 fleet: Optional[FleetConfig] = None, *,
                 chips_per_replica: int = 1,
                 clock=time.perf_counter, sleep=time.sleep,
                 worker_env: Optional[Dict[str, str]] = None,
                 worker_cmd: Optional[Callable] = None):
        self.params = params
        self.config = config
        self.fleet = fleet if fleet is not None else FleetConfig()
        self.chips_per_replica = chips_per_replica
        self.chips = chips_per_replica * self.fleet.replicas
        self.clock = clock
        self._sleep = sleep

        # Static admission geometry (survives every replica dying):
        # exactly PagedKVCache.fits, computed off params + config —
        # capacity derived from the kvcache module's own constant so
        # router and engines can never disagree on the reserved count.
        from horovod_tpu.serve.kvcache import allocatable_pages

        self._lmax = int(params["pos"].shape[0])
        self._page_capacity = allocatable_pages(config.num_pages)

        # Router state.
        self.queue: List[Request] = []
        self.rejected: List[Request] = []
        self.finished: List[Request] = []
        self.timed_out: List[Request] = []
        self.evicted: List[Request] = []    # engine-terminal evictions
        # admit->finish secs feeding retry_after_hint — a BOUNDED
        # recency window, not the full history: the hint is recomputed
        # on every overloaded rejection (hot exactly when shedding is),
        # and recent service times describe a degraded fleet better
        # than its lifetime average anyway.
        import collections

        self._service_samples = collections.deque(maxlen=256)

        # Recovery metrics.
        self.incidents: List[Dict] = []
        self.incidents_by_class: Dict[str, int] = {}
        self.redispatched_total = 0
        self.tokens_recomputed_total = 0
        #: Drain-time recompute tokens the surviving replica's prefix
        #: cache actually SKIPPED (banked per completed redispatch
        #: cycle; the live remainder is computed in stats()).
        self.redispatch_prefix_saved = 0
        self.shed_total = 0
        self.restarts_used = 0

        self.occupancy_samples: List[float] = []
        self.steps = 0
        self._t_start = clock()

        # Fault plan (armed via arm_fault_plan; fires on the clock).
        self._pending_faults: List[tuple] = []   # (fire_at_s, action)
        self._fault_t0: Optional[float] = None

        # Supervision: heartbeat dir namespaced per fleet INSTANCE so
        # colocated fleets/supervisors never watch each other's files.
        self.heartbeat_dir = namespaced_heartbeat_dir(
            self.fleet.heartbeat_dir)
        self.watchdog: Optional[HealthWatchdog] = None
        if self.fleet.watchdog_timeout > 0:
            self.watchdog = HealthWatchdog(
                self.heartbeat_dir, self.fleet.watchdog_timeout,
                interval=min(0.5, self.fleet.watchdog_timeout / 2))

        # Versioned weights: ONE content-addressed artifact per
        # version (serve/params_wire.py — deterministic blob, sha256,
        # chunked-transfer manifest), built for every transport so
        # digests and version bookkeeping are uniform. Wire transports
        # (process/tcp) push it to every worker incarnation at spawn —
        # params never touch a filesystem any other process reads —
        # and update_params() rolls the fleet to a new version one
        # replica at a time.
        self.params_version = 1
        self._artifact = self._build_artifact(params, 1)
        self._config_payload = dataclasses.asdict(config)
        self.push_stats: Dict = {"pushes": 0, "bytes": 0, "chunks": 0,
                                 "retries": 0, "ms": 0.0}
        self.transfer_incidents: Dict[str, int] = {}
        self.version_recomputed = 0
        self._update: Optional[Dict] = None

        # Process-transport plumbing: one workdir per fleet INSTANCE
        # (Unix socket paths ONLY — config and params reach every
        # worker over the wire), per-call RPC wall samples (overhead
        # evidence, shared across incarnations), and the transport-
        # failure incident counters. ``worker_cmd(rid, sock_path,
        # default) -> (argv, env)`` is the spawn injection point
        # (custom containers, the protocol-stub test worker); it
        # receives the default ``(argv, env)`` to tweak or replace.
        # ``worker_env`` overlays the inherited environment of the
        # default command.
        self._workdir: Optional[str] = None
        self._rpc_samples: List[float] = []
        self.transport_incidents: Dict[str, int] = {}
        self._incarnations: Dict[int, int] = {}
        self._worker_env = dict(worker_env or {})
        self._worker_cmd = worker_cmd
        # TCP placement: the parsed host table — each entry one
        # FAILURE DOMAIN: {"name", "port" (base or None=probe-free,
        # local only), "local", "faults" (the shared NetFaults every
        # connection to the host routes through — one NIC, one fate)}.
        self._hosts: List[Dict] = []
        self._secret: Optional[str] = None
        if self.fleet.transport == "process":
            import tempfile

            self._workdir = tempfile.mkdtemp(prefix="hvd-fleet-")
        if self.fleet.transport == "tcp":
            from horovod_tpu.run.network import make_secret_key
            from horovod_tpu.serve.config import (LOCAL_HOSTS,
                                                  parse_host_entry)
            from horovod_tpu.serve.netfault import NetFaults

            # One ephemeral shared secret per fleet instance: every
            # TCP connection must pass the handshake before an RPC is
            # served. It reaches workers through the environment
            # (ssh placement ships it over stdin, never argv).
            self._secret = make_secret_key().hex()
            for entry in (self.fleet.hosts or ("127.0.0.1",)):
                name, port = parse_host_entry(entry)
                self._hosts.append({
                    "name": name, "port": port,
                    "local": name in LOCAL_HOSTS,
                    "faults": NetFaults(),
                })

        self._closed = False
        self.replicas: List[Replica] = []
        try:
            for i in range(self.fleet.replicas):
                rep = self._spawn(i)
                rep.role = self.fleet.pool_of(i)
                self.replicas.append(rep)
        except BaseException:
            # A failed spawn mid-constructor must not orphan the
            # replicas (real OS processes!) already running — close()
            # is unreachable when __init__ raises.
            for rep in self.replicas:
                rep.ensure_dead(0)
            import shutil

            shutil.rmtree(self.heartbeat_dir, ignore_errors=True)
            if self._workdir:
                shutil.rmtree(self._workdir, ignore_errors=True)
            raise

        # Disaggregated prefill/decode: the KV-handoff coordinator
        # (serve/disagg.py) runs once per tick after every replica
        # stepped. None = colocated, zero new code paths.
        self.disagg = None
        if self.fleet.pools is not None:
            from horovod_tpu.serve.disagg import DisaggCoordinator

            self.disagg = DisaggCoordinator(self)

    def close(self) -> None:
        """Tear the fleet down and release its host-side footprint.
        Idempotent; a closed fleet can no longer step.

        For REAL children (``transport="process"``) this is the no-
        zombies contract: every worker gets a graceful ``shutdown``
        RPC under ``FleetConfig.shutdown_deadline``, then the SIGTERM →
        SIGKILL escalation, and is REAPED — including replicas whose
        engine thread is wedged by a ``stall:`` fault (their RPC
        thread still answers, and a worker dead on both planes falls
        through to the signals; regression-pinned in tests). Then the
        per-instance heartbeat directory and process-transport workdir
        (sockets, params/config files) are removed — uniquely named by
        construction, so a long-lived service or bench loop
        constructing fleets repeatedly never accumulates orphans.
        Context-manager form closes on exit."""
        if self._closed:
            return
        self._closed = True
        for rep in self.replicas:
            rep.shutdown(self.fleet.shutdown_deadline)
        import shutil

        shutil.rmtree(self.heartbeat_dir, ignore_errors=True)
        if self._workdir:
            shutil.rmtree(self._workdir, ignore_errors=True)

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------- lifecycle

    def _build_artifact(self, params: Dict, version: int) -> Dict:
        """One content-addressed, versioned transfer artifact (blob +
        manifest + sha256) — the single source every push, digest
        verify, and version stamp reads."""
        blob = params_wire.params_to_blob(params)
        manifest = params_wire.make_manifest(
            blob, version=version,
            chunk_bytes=self.fleet.push_chunk_bytes)
        return {"blob": blob, "manifest": manifest,
                "sha256": manifest["sha256"], "version": version}

    def _spawn(self, rid: int) -> Replica:
        if self.fleet.transport == "tcp":
            # No heartbeat FILE: a remote worker's file is on another
            # machine — liveness rides the transport instead.
            return self._spawn_tcp(rid)
        hb = Heartbeat(self.heartbeat_dir, rank=rid)
        # A (re)spawned replica is unwatched until its first completed
        # step: no stale file from a previous incarnation may insta-kill
        # it while it recompiles.
        try:
            os.unlink(hb.path)
        except OSError:
            pass
        if self.fleet.transport == "process":
            return self._spawn_process(rid, hb)
        engine = ServeEngine(self.params, self.config,
                             chips=self.chips_per_replica,
                             clock=self.clock)
        rep = Replica(rid, engine, hb)
        # In-process engines share the fleet's params object directly —
        # no wire, so the version stamp lands at spawn.
        rep.version = self.params_version
        rep.params_sha = self._artifact["sha256"]
        return rep

    def _default_worker_cmd(self, rid: int, sock_path: str):
        # No --params/--config: config and weights arrive over the
        # wire (put_config + the chunked push RPCs) — a worker
        # incarnation reads NOTHING the fleet wrote to a filesystem.
        cmd = [sys.executable, "-m", "horovod_tpu.serve.worker",
               "--socket", sock_path,
               "--rank", str(rid),
               "--heartbeat-dir", self.heartbeat_dir]
        env = dict(os.environ)
        env.update(self._worker_env)
        return cmd, env

    def _spawn_process(self, rid: int, hb: Heartbeat) -> ProcessReplica:
        from horovod_tpu.run import spawn_worker

        # Per-incarnation socket path: a relaunch must never race the
        # dead incarnation's stale socket file.
        inc = self._incarnations.get(rid, 0) + 1
        self._incarnations[rid] = inc
        sock_path = os.path.join(self._workdir, f"r{rid}-{inc}.sock")
        default = self._default_worker_cmd(rid, sock_path)
        cmd, env = (self._worker_cmd(rid, sock_path, default)
                    if self._worker_cmd is not None else default)
        proc = spawn_worker(cmd, env)
        client = RpcClient(
            sock_path, default_timeout=self.fleet.rpc_deadline,
            connect_timeout=self.fleet.spawn_timeout,
            proc_alive=lambda: proc.poll() is None,
            call_ms=self._rpc_samples)
        proxy = _EngineProxy(client, self.config, self._fits,
                             self.clock)
        _log(f"replica {rid}: spawned worker pid {proc.pid} "
             f"(incarnation {inc}) on {sock_path}")
        return ProcessReplica(rid, proxy, hb, proc, client, sock_path)

    @staticmethod
    def _free_local_port() -> int:
        import socket as _socket

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _spawn_tcp(self, rid: int) -> TcpReplica:
        """One TCP worker on its assigned host. Replicas spread
        round-robin over the host table (``rid % hosts``); a host with
        a base port gives its ``k``-th worker ``base + k`` (stable
        across relaunches — the worker binds with ``SO_REUSEADDR``),
        while local auto-port hosts get a fresh probed free port per
        incarnation. Remote hosts spawn over ssh (the launcher's
        pty-HUP kill discipline). The worker starts with NOTHING from
        any filesystem: ServeConfig and the versioned params artifact
        arrive over the wire (``_init_due`` → ``_push_artifact``), so
        multi-host placement assumes no shared working filesystem at
        all."""
        from horovod_tpu.run import spawn_worker, spawn_worker_ssh

        h = rid % len(self._hosts)
        slot = rid // len(self._hosts)   # k-th worker on this host
        host = self._hosts[h]
        inc = self._incarnations.get(rid, 0) + 1
        self._incarnations[rid] = inc
        if host["port"] is not None:
            port = host["port"] + slot
        else:
            port = self._free_local_port()
        bind_host = "127.0.0.1" if host["local"] else "0.0.0.0"
        endpoint = f"{bind_host}:{port}"
        cmd = [sys.executable, "-m", "horovod_tpu.serve.worker",
               "--bind", endpoint,
               "--rank", str(rid)]
        env = dict(os.environ)
        env.update(self._worker_env)
        env["HOROVOD_SECRET"] = self._secret
        if self._worker_cmd is not None:
            cmd, env = self._worker_cmd(rid, endpoint, (cmd, env))
        if host["local"]:
            proc = spawn_worker(cmd, env)
        else:
            proc = spawn_worker_ssh(host["name"], cmd, env)
        connect_host = "127.0.0.1" if host["local"] else host["name"]
        client = RpcClient(
            (connect_host, port),
            default_timeout=self.fleet.rpc_deadline,
            connect_timeout=self.fleet.spawn_timeout,
            proc_alive=lambda: proc.poll() is None,
            call_ms=self._rpc_samples,
            secret=self._secret,
            sock_wrap=host["faults"].wrap)
        proxy = _EngineProxy(client, self.config, self._fits,
                             self.clock)
        _log(f"replica {rid}: spawned tcp worker pid {proc.pid} "
             f"(incarnation {inc}) on host {h} ({host['name']}) "
             f"port {port}" + (" via ssh" if not host["local"] else ""))
        rep = TcpReplica(rid, proxy, proc, client,
                         f"{connect_host}:{port}", h, host["name"],
                         via_ssh=not host["local"])
        # The liveness channel starts "fresh now": a spawned worker is
        # unwatched until its heartbeat sequence first moves, aged
        # from spawn time — the same no-insta-kill grace the file
        # watchdog gets by unlinking the stale heartbeat.
        rep.hb_at = self.clock()
        return rep

    # --------------------------------------- wire weight distribution

    def _proc_dead(self, rep: Replica) -> bool:
        proc = getattr(rep, "proc", None)
        return proc is not None and proc.poll() is not None

    def _push_artifact(self, rep: Replica,
                       include_config: bool = False) -> None:
        """Stream the CURRENT params artifact to one wire replica in
        bounded chunks: manifest first (``push_begin`` returns the
        worker's verified resume offset), then per-chunk-CRC'd chunks,
        then ``push_commit`` — the worker digest-verifies the whole
        artifact and atomically renames it into place, and the fleet
        verifies the returned sha256 against its own.

        THE one exception to the no-RPC-retry rule: chunk writes are
        idempotent (same bytes at the same offset, contiguity
        enforced, digest at commit), so a torn or corrupted transfer
        is a typed failure that RETRIES — resume-from-offset under the
        fleet's budgeted exponential backoff (``push_retries``) —
        never a silently wrong model and never an instant replica
        death. Past the budget (or with the worker process observably
        dead) the error propagates and the caller routes the ordinary
        replica-death path.

        Honest limitation: the transfer (and its retry backoff) runs
        SYNCHRONOUSLY inside the fleet tick — for CI-scale artifacts
        this is milliseconds, but a multi-GB push stalls the other
        replicas' stepping for its duration. Chunking the transfer
        ACROSS ticks (the relaunch path's schedule-and-return pattern)
        is the named follow-up when artifact sizes demand it."""
        art = self._artifact
        man = art["manifest"]
        client = rep.engine.client
        fault, rep.push_fault = rep.push_fault, None
        attempts = 0
        t0 = self.clock()
        chunks_sent = 0
        cb, n = man["chunk_bytes"], man["num_chunks"]
        while True:
            try:
                if include_config:
                    client.call("put_config",
                                {"config": dict(self._config_payload)})
                have = int(client.call(
                    "push_begin", {"manifest": man})["have_bytes"])
                if have:
                    _log(f"replica {rep.id}: resuming params push at "
                         f"byte {have}/{man['total_bytes']} (the "
                         "worker's verified prefix survives the torn "
                         "transfer)")
                for i in range(have // cb, n):
                    chunk = params_wire.make_chunk(art["blob"], man, i)
                    if fault is not None and i >= min(max(1, n // 2),
                                                      n - 1):
                        # Consume the one-shot BEFORE applying it: the
                        # tear raises, and a retry must resume clean,
                        # not re-tear forever into the death path.
                        armed, fault = fault, None
                        chunk = self._push_fault_chunk(
                            rep, armed, chunk, i, n, client)
                    client.call("push_chunk", chunk)
                    chunks_sent += 1
                res = client.call("push_commit",
                                  {"version": man["version"]})
                if res.get("sha256") != man["sha256"]:
                    raise ChecksumError(
                        f"push_commit digest {res.get('sha256')!r} != "
                        f"artifact {man['sha256']} — the worker "
                        "assembled a different artifact")
                break
            except TransportError as e:
                kind = remote_error_kind(e)
                self.transfer_incidents[kind] = \
                    self.transfer_incidents.get(kind, 0) + 1
                attempts += 1
                if attempts > self.fleet.push_retries \
                        or self._proc_dead(rep):
                    _log(f"replica {rep.id}: params push failed "
                         f"({kind}: {e}) with no budget left — "
                         "routing into the replica-death path")
                    raise
                # Counted AFTER the budget gate: "retries" are resumes
                # that actually ran, not the terminal failed attempt
                # (transfer_incidents records every observation).
                self.push_stats["retries"] += 1
                backoff = min(self.fleet.backoff_cap,
                              self.fleet.backoff_base
                              * (2 ** (attempts - 1)))
                _log(f"replica {rep.id}: params push attempt "
                     f"{attempts} failed ({kind}: {e}) — classified "
                     f"transfer retry, resuming from the worker's "
                     f"verified offset in {backoff:g}s")
                self._sleep(backoff)
        rep.version = man["version"]
        rep.params_sha = man["sha256"]
        self.push_stats["pushes"] += 1
        self.push_stats["bytes"] += man["total_bytes"]
        self.push_stats["chunks"] += chunks_sent
        self.push_stats["ms"] += round((self.clock() - t0) * 1e3, 3)

    def _push_fault_chunk(self, rep: Replica, fault: str, chunk: Dict,
                          i: int, n: int, client) -> Dict:
        """Apply an already-consumed transfer:/corrupt: fault to the
        push's mid-stream chunk. ``corrupt`` returns a chunk whose
        payload no longer matches its own crc32 — the worker MUST
        reject it typed; ``transfer`` tears the connection mid-push —
        the retry must resume from the worker's verified offset."""
        import base64 as _b64

        if fault == "corrupt":
            raw = bytearray(_b64.b64decode(chunk["data"]))
            raw[0] ^= 0x01
            _log(f"fault injection: corrupt: flipping a bit in chunk "
                 f"{i}/{n} of the push to replica {rep.id}")
            return dict(chunk,
                        data=_b64.b64encode(bytes(raw)).decode("ascii"))
        _log(f"fault injection: transfer: tearing the push to replica "
             f"{rep.id} after {i}/{n} chunks")
        client.close()
        raise ConnectionLost(
            f"transfer fault injection: connection torn mid-push "
            f"after {i}/{n} chunks")

    def _init_due(self, now: float) -> None:
        """Wire-init any healthy replica that has no weights yet (a
        fresh spawn or relaunch): ship ServeConfig + the current
        artifact over its RPC wire. A failed init (worker dead on
        startup, push budget exhausted) is the ordinary classified
        replica-death path — it consumes restart budget exactly like
        the old first-step failure did."""
        if self.fleet.transport == "inproc":
            return
        for rep in self.replicas:
            if not rep.healthy or rep.version is not None:
                continue
            try:
                self._push_artifact(rep, include_config=True)
            except TransportError as e:
                self._transport_death(rep, e, now)
                continue
            _log(f"replica {rep.id}: wire-init complete — params "
                 f"v{rep.version} (sha256 {rep.params_sha[:12]}) "
                 "digest-verified over the transport")

    # --------------------------------------------- rolling updates

    @property
    def update_active(self) -> bool:
        return self._update is not None

    def update_params(self, params: Dict) -> int:
        """Arm a ZERO-DOWNTIME rolling weight update; returns the new
        version. The roll itself advances inside :meth:`step`, one
        replica at a time: stop routing to it → let its in-flight
        requests finish (drain) → push the new artifact over the wire
        (or swap in place, inproc) → verify the digest → readmit.
        Requests already streaming stay PINNED to the version they
        started on (the router only redispatches them onto
        same-version replicas; see ``Request.version``), so a weight
        mix mid-stream is impossible by construction. Replicas that
        are dead when the roll reaches them pick the new version up at
        relaunch — every relaunch wire-inits from the CURRENT
        artifact."""
        if self._closed:
            raise RuntimeError("update_params on a closed ServeFleet")
        if self._update is not None:
            raise RuntimeError(
                "a rolling update is already in progress — one version "
                "boundary at a time (wait for update_active to clear)")
        version = self.params_version + 1
        art = self._build_artifact(params, version)
        # Geometry gate BEFORE any state mutates: the blob header is
        # the complete structural fingerprint (the full pytree spec —
        # every key and nesting — plus per-leaf shapes/dtypes), so a
        # wrong-shaped OR restructured update raises HERE — never
        # after the artifact swap, where it would crash-loop every
        # relaunch (wire) or escape the fleet loop mid-roll (inproc).
        # A geometry change is a new fleet, not a weight roll.
        if params_wire.blob_spec(art["blob"]) != \
                params_wire.blob_spec(self._artifact["blob"]):
            raise ValueError(
                "update_params geometry mismatch: the new params' tree "
                "structure or leaf shapes/dtypes differ from the "
                "serving artifact's — a rolling update swaps WEIGHTS "
                "under the compiled programs; a geometry change needs "
                "a fresh fleet")
        self.params = params
        self.params_version = version
        self._artifact = art
        self._update = {"version": version, "params": params,
                        "current": None, "t0": self.clock()}
        _log(f"rolling update to params v{version} (sha256 "
             f"{art['sha256'][:12]}) armed — one replica at a time, "
             "version-pinned streams keep decoding")
        return version

    def _advance_update(self, now: float) -> None:
        """One tick of the rolling update's state machine (see
        :meth:`update_params`): pick the next non-updated healthy
        replica, stop routing to it, wait for its in-flight requests
        to finish, push + digest-verify + readmit, repeat. A replica
        already drained updates in the SAME tick it is picked; one
        that is still serving drains across ticks while its peers
        carry the traffic."""
        u = self._update
        if u is None:
            return
        while True:
            rep = u["current"]
            if rep is None:
                for cand in self.replicas:
                    if cand.healthy and cand.version is not None \
                            and cand.version != u["version"]:
                        cand.accepting = False
                        u["current"] = cand
                        _log(f"rolling update: draining replica "
                             f"{cand.id} (v{cand.version} → "
                             f"v{u['version']}; {len(cand.assigned)} "
                             "in flight finish first)")
                        break
                else:
                    # No healthy replica left behind the target: the
                    # roll is complete (dead/uninitialized replicas
                    # wire-init from the new artifact at relaunch).
                    if all(r.version == u["version"] or not r.healthy
                           or r.version is None
                           for r in self.replicas):
                        _log(f"rolling update to params "
                             f"v{u['version']} complete in "
                             f"{self.clock() - u['t0']:.3f}s")
                        self._update = None
                    return
                continue
            if rep.state != "healthy":
                # Died mid-drain/push: its relaunch wire-inits from
                # the new artifact; move on.
                rep.accepting = True
                u["current"] = None
                continue
            if rep.assigned:
                return   # still draining: in-flight requests finish
            try:
                if rep.transport == "inproc":
                    rep.engine.update_params(u["params"])
                    rep.version = u["version"]
                    rep.params_sha = self._artifact["sha256"]
                    self.push_stats["pushes"] += 1
                else:
                    self._push_artifact(rep)
            except TransportError as e:
                self._transport_death(rep, e, now)
                rep.accepting = True
                u["current"] = None
                return
            rep.accepting = True
            u["current"] = None
            _log(f"replica {rep.id}: updated to params "
                 f"v{rep.version} (digest verified) — readmitted")

    @property
    def in_flight(self) -> int:
        return sum(len(r.assigned) for r in self.replicas) + \
            len(self.queue)

    @property
    def idle(self) -> bool:
        return self.in_flight == 0

    @property
    def alive(self) -> bool:
        """At least one replica is serving or can still come back."""
        return any(r.state != "failed" for r in self.replicas)

    # ------------------------------------------------------ fault plan

    def arm_fault_plan(self, plan: Union[str, Sequence[ServeFaultAction]],
                       horizon: Optional[float] = None) -> None:
        """Arm a serving fault plan (string grammar or parsed actions).
        Fire offsets are measured from the fault epoch — the fleet's
        first step, re-anchored only by :meth:`reset_metrics` (the
        bench's measurement start) — NEVER by arming itself: a second
        mid-run arm must not silently shift the fire times of actions
        already armed. An offset already in the past fires at the next
        step. ``horizon`` resolves percent ``at=`` forms (e.g. the
        bench passes its last workload arrival); replica ids are
        validated against the fleet size fail-fast."""
        actions = (parse_serve_fault_plan(plan)
                   if isinstance(plan, str) else list(plan))
        for a in actions:
            # Hand-built actions get the parser's fail-fast contract
            # too — a malformed one must raise HERE, not TypeError
            # out of the fleet loop at fire time.
            a.validate()
            if a.replica is not None and \
                    not 0 <= a.replica < len(self.replicas):
                raise FaultPlanError(
                    f"fault action {a}: replica {a.replica} is outside "
                    f"this fleet (replicas 0..{len(self.replicas) - 1})")
            if a.kind in ("transfer", "corrupt") \
                    and self.fleet.transport == "inproc":
                raise FaultPlanError(
                    f"fault action {a}: {a.kind} faults address the "
                    "params-push wire — the inproc transport has none "
                    "(use transport='process' or 'tcp')")
            if a.host is not None:
                if self.fleet.transport != "tcp":
                    raise FaultPlanError(
                        f"fault action {a}: host-addressed faults need "
                        f"the tcp transport (this fleet is "
                        f"{self.fleet.transport!r} — hosts are not a "
                        "failure domain there)")
                if not 0 <= a.host < len(self._hosts):
                    raise FaultPlanError(
                        f"fault action {a}: host {a.host} is outside "
                        f"this fleet (hosts 0..{len(self._hosts) - 1})")
        self._pending_faults.extend(
            (a.resolve_at(horizon), a) for a in actions)
        self._pending_faults.sort(key=lambda p: p[0])

    def _inject_faults(self, now: float) -> None:
        if not self._pending_faults:
            return
        t = now - self._fault_t0
        while self._pending_faults and self._pending_faults[0][0] <= t:
            _, action = self._pending_faults.pop(0)
            if action.host is not None:
                _log(f"fault injection: {action} firing")
                if action.kind == "kill":
                    # The machine-loss shape: every worker on the host
                    # SIGKILLed (through the ssh pty for remote ones),
                    # one host_down incident, one mass redispatch.
                    self._host_down(action.host, now, cause="kill")
                elif action.kind == "partition":
                    # The NIC-loss shape: every connection to the host
                    # goes dark via the shared NetFaults state at the
                    # transport seam; detection happens organically —
                    # a deadline expiry or the half-open reset when
                    # the window ends — and the probe sweep coalesces
                    # the loss into host_down.
                    self._hosts[action.host]["faults"].partition(
                        action.secs)
                continue
            rep = self.replicas[action.replica]
            _log(f"fault injection: {action} firing (replica state "
                 f"{rep.state})")
            if action.kind == "kill":
                if rep.healthy:
                    # ensure_dead (inside _kill_replica) makes this a
                    # GENUINE os.kill(pgid, SIGKILL) on a process
                    # replica — the observed exit code is the real -9.
                    self._kill_replica(rep, code=-int(_signal.SIGKILL),
                                       stalled=False, now=now)
            elif action.kind == "stall":
                if rep.healthy:
                    self._arm_replica_fault(
                        rep, now, "stall", {"secs": action.secs},
                        lambda: setattr(
                            rep, "stall_until",
                            now + action.secs
                            if action.secs is not None
                            else float("inf")))
            elif action.kind == "slow":
                # Like kill/stall: a fault addressed to a dead replica
                # is a no-op — it must not brand the NEXT incarnation
                # (kill resets slow_factor to 1.0 for the same reason).
                if rep.healthy:
                    self._arm_replica_fault(
                        rep, now, "slow", {"factor": action.factor},
                        lambda: setattr(rep, "slow_factor",
                                        float(action.factor)))
            elif action.kind in ("transfer", "corrupt"):
                # Armed on the REPLICA, consumed one-shot by its next
                # params push (a spawn/relaunch wire-init or the
                # rolling update's roll reaching it).
                if rep.healthy:
                    rep.push_fault = action.kind

    def _arm_replica_fault(self, rep: Replica, now: float, kind: str,
                           payload: Dict, inproc_apply) -> None:
        """Route one stall/slow fault to where the replica actually
        lives: in-process replicas flip the fleet-side flags; a process
        worker is told over RPC and wedges/slows ITSELF (a stalled
        process is then genuinely silent — only its stale heartbeat
        gives it away). A transport failure while arming is, as
        always, replica death."""
        if rep.transport != "process":
            inproc_apply()
            return
        try:
            rep.engine.client.call("fault", dict(payload, kind=kind))
        except TransportError as e:
            self._transport_death(rep, e, now)

    # ------------------------------------------------------ submission

    def _fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """PagedKVCache.fits without a live engine — the SAME
        :func:`~horovod_tpu.serve.kvcache.fits_geometry` predicate, so
        admission control keeps answering (and rejecting honestly)
        while every replica is mid-relaunch and can never drift from
        what the engines would admit."""
        from horovod_tpu.serve.kvcache import fits_geometry

        return fits_geometry(prompt_len, max_new_tokens,
                             max_len=self._lmax,
                             page_size=self.config.page_size,
                             capacity=self._page_capacity)

    def _healthy_slots(self) -> int:
        return sum(r.engine.config.decode_slots for r in self.replicas
                   if r.healthy and r.engine is not None)

    def _reject(self, req: Request, reason: str,
                retry_after: Optional[float] = None) -> Request:
        req.state = RequestState.REJECTED
        req.reject_reason = reason
        req.retry_after = retry_after
        self.rejected.append(req)
        if reason == "overloaded":
            self.shed_total += 1
        return req

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_token: Optional[int] = None, seed: int = 0,
               arrival: Optional[float] = None,
               ttl: Optional[float] = None) -> Request:
        """Admit one request at the router (same surface as
        :meth:`ServeEngine.submit`). Check ``state`` — ``rejected``
        carries ``reject_reason`` (``infeasible``: can never run on
        this geometry; ``overloaded``: the bounded queue is full or the
        fleet is permanently down — retry after ``retry_after`` when
        it is not None)."""
        from horovod_tpu.serve.scheduler import make_request

        req = make_request(self.config, self.clock, prompt,
                           max_new_tokens, temperature=temperature,
                           top_k=top_k, eos_token=eos_token, seed=seed,
                           arrival=arrival, ttl=ttl)
        if not self._fits(req.prompt_len, req.max_new_tokens):
            return self._reject(req, "infeasible")
        if not self.alive:
            # Permanently degraded to zero replicas: shed with no hint
            # (there is no "later" this fleet can promise).
            return self._reject(req, "overloaded")
        if self.fleet.max_queue and \
                len(self.queue) >= self.fleet.max_queue:
            hint = retry_after_hint(
                len(self.queue), max(1, self._healthy_slots()),
                self._service_samples, self.fleet.retry_after_min)
            return self._reject(req, "overloaded", round(hint, 4))
        req.state = RequestState.QUEUED
        self.queue.append(req)
        return req

    # ---------------------------------------------------- supervision

    def _probe_alive(self, rep: Replica, budget: float = 1.0):
        """Short-deadline reachability probe of one replica (the
        host-domain sweep after a peer's transport death). Returns
        None when alive, else the typed failure's class name."""
        try:
            rep.engine.client.call(
                "ping", timeout=min(budget, self.fleet.rpc_deadline))
            return None
        except TransportError as e:
            return type(e).__name__

    def _transport_death(self, rep: Replica, err: Exception,
                         now: float) -> None:
        """The tentpole's one rule: ANY transport failure — refused
        connect, torn frame, checksum mismatch, deadline expiry,
        remote raise — is the replica-death path, never an RPC retry
        (a blind resend could double-apply a submit and break
        at-most-once). ``ensure_dead`` inside the kill path turns the
        maybe-still-running worker into a definitely-dead one and
        recovers its real exit code for classification.

        On the TCP transport the replica's HOST is the suspect: the
        fleet immediately probes the host's other live replicas with a
        short ping, and when the whole host is unreachable (>= 2
        replicas failing together) the loss is ONE classified
        ``host_down`` incident — every replica of the host drains and
        redispatches in the same sweep, instead of N separate
        incidents trickling in one deadline at a time."""
        kind = type(err).__name__
        self.transport_incidents[kind] = \
            self.transport_incidents.get(kind, 0) + 1
        _log(f"replica {rep.id}: transport failure {kind}: {err} — "
             "routing into the replica-death path (no retry)")
        if rep.host is not None:
            peers = [r for r in self.replicas
                     if r is not rep and r.healthy
                     and r.host == rep.host]
            dead_peers = [(p, self._probe_alive(p)) for p in peers]
            dead_peers = [(p, k) for p, k in dead_peers if k is not None]
            if peers and len(dead_peers) == len(peers):
                # The whole host is dark — one incident, one drain.
                self._host_down(rep.host, now, cause="transport",
                                transport_error=kind)
                return
            # A partial sweep: the trigger dies, and so does any peer
            # the probe found dead — each its own classified incident.
            self._kill_replica(rep, code=1, stalled=False, now=now,
                               transport_error=kind)
            for p, pkind in dead_peers:
                self.transport_incidents[pkind] = \
                    self.transport_incidents.get(pkind, 0) + 1
                self._kill_replica(p, code=1, stalled=False, now=now,
                                   transport_error=pkind)
            return
        self._kill_replica(rep, code=1, stalled=False, now=now,
                           transport_error=kind)

    def _host_down(self, h: int, now: float, *, cause: str,
                   transport_error: Optional[str] = None,
                   detect_age: Optional[float] = None) -> None:
        """A whole host is one failure domain: kill, drain and
        redispatch EVERY healthy replica placed on it as a single
        classified ``host_down`` incident (``kill:host=`` faults land
        here directly; transport-detected losses arrive via
        :meth:`_transport_death`'s probe sweep). Each replica still
        relaunches individually under the fleet-wide restart budget —
        a host that comes back simply receives its workers again."""
        host = self._hosts[h]
        reps = [r for r in self.replicas
                if r.healthy and r.host == h]
        if not reps:
            return
        self.incidents_by_class["host_down"] = \
            self.incidents_by_class.get("host_down", 0) + 1
        details = []
        total_moved = total_rec = 0
        max_backoff = 0.0
        code_hint = -int(_signal.SIGKILL) if cause == "kill" else 1
        for rep in reps:
            code, moved, recomputed, backoff = self._kill_replica(
                rep, code=code_hint, stalled=False, now=now,
                transport_error=transport_error, record=False)
            details.append({"replica": rep.id, "code": code})
            total_moved += moved
            total_rec += recomputed
            max_backoff = max(max_backoff, backoff)
        self.incidents.append({
            "replica": None,
            "host": h,
            "host_name": host["name"],
            "category": "host_down",
            "cause": cause,
            "code": details[0]["code"],
            "replicas": details,
            "transport_error": transport_error,
            "t_s": round(now - self._t_start, 4),
            "detect_s": round(detect_age, 4) if detect_age is not None
            else 0.0,
            "redispatched": total_moved,
            "tokens_recomputed": total_rec,
            "backoff_s": round(max_backoff, 4),
        })
        _log(f"host {h} ({host['name']}) down ({cause}"
             + (f": {transport_error}" if transport_error else "")
             + f") — {len(reps)} replica(s) lost in one incident, "
             f"{total_moved} request(s) drained to survivors "
             f"({total_rec} KV tokens to recompute)")

    def _kill_replica(self, rep: Replica, *, code: int, stalled: bool,
                      now: float, detect_age: Optional[float] = None,
                      transport_error: Optional[str] = None,
                      record: bool = True) -> tuple:
        """Classify + drain + schedule relaunch: the fleet edition of
        the supervisor's per-incident policy. ``record=False`` (the
        host-incident path) suppresses the per-replica incident entry
        and class count — the caller owns the single aggregate record
        — and returns ``(code, moved, recomputed, backoff)`` either
        way."""
        # Make the failure domain REALLY dead first (process replicas:
        # SIGKILL the worker's process group + reap — no zombies, and
        # the reaped code beats the synthetic hint as evidence).
        code = rep.ensure_dead(code)
        rep.exit = WorkerExit(rank=rep.id, code=code, stalled=stalled)
        category = rep.exit.category
        moved, recomputed = self._drain(rep, now)
        # The engine object (pages, allocator, compiled-step cache) is
        # dropped wholesale — the crash shape. Its heartbeat file goes
        # too so the relaunch starts unwatched.
        rep.engine = None
        rep.state = "dead"
        rep.stall_until = None
        rep.slow_factor = 1.0
        rep.hb_seq = None
        rep.hb_at = None
        rep.accepting = True     # the relaunch serves; pins re-gate it
        rep.push_fault = None    # a one-shot fault never brands the
        #                          next incarnation
        if rep.heartbeat is not None:
            try:
                os.unlink(rep.heartbeat.path)
            except OSError:
                pass
        backoff = min(self.fleet.backoff_cap,
                      self.fleet.backoff_base * (2 ** rep.restarts))
        rep.relaunch_at = now + backoff
        if record:
            self.incidents_by_class[category] = \
                self.incidents_by_class.get(category, 0) + 1
            self.incidents.append({
                "replica": rep.id,
                "category": category,
                "code": code,
                "transport_error": transport_error,
                "t_s": round(now - self._t_start, 4),
                # Watchdog kills carry the observed heartbeat age (real
                # detection latency). In-process crashes are observed
                # synchronously — 0.0 is honest here where a
                # multi-process fleet would pay one supervision-poll
                # interval.
                "detect_s": round(detect_age, 4)
                if detect_age is not None else 0.0,
                "redispatched": moved,
                "tokens_recomputed": recomputed,
                "backoff_s": round(backoff, 4),
            })
        _log(f"{rep.exit.describe(role='replica')} — drained {moved} "
             f"request(s) to survivors ({recomputed} KV tokens to "
             f"recompute); relaunch in {backoff:g}s")
        return code, moved, recomputed, backoff

    def _drain(self, rep: Replica, now: float) -> tuple:
        """Recover every dispatched-but-unfinished request of a dead
        replica from ROUTER bookkeeping: rebase generated-so-far into
        the prompt and requeue at the HEAD (they already consumed
        service), preserving their relative order. Returns
        ``(redispatched, kv_tokens_to_recompute)``."""
        moved: List[Request] = []
        recomputed = 0
        terminal = {
            RequestState.FINISHED: self.finished,
            RequestState.TIMEOUT: self.timed_out,
            RequestState.REJECTED: self.rejected,
            RequestState.EVICTED: self.evicted,
        }
        for req in rep.assigned:
            dest = terminal.get(req.state)
            if dest is not None:
                # Terminal but not yet collected — the replica died in
                # the very step that finished/expired it, before the
                # end-of-tick _collect ran (e.g. its engine raised
                # mid-step). The router's streamed-token truth stands:
                # route it to the fleet list, never drop it.
                if not any(r is req for r in dest):
                    dest.append(req)
                continue
            # The dead engine's pages died with it; only the request's
            # host-side bookkeeping survives.
            req.pages = []
            req.page_table = None
            recomputed += req.prefill_pos + len(req.generated)
            # Redispatch-meets-prefix bookkeeping: `recomputed` is the
            # honest PESSIMISTIC count at detection time; hits the
            # survivor's prefix cache lands past this snapshot are
            # tokens never actually recomputed, and stats() nets them
            # out. A re-drain first banks the previous cycle's gains.
            if req.prefix_hits_at_drain is not None:
                self.redispatch_prefix_saved += max(
                    0, req.prefix_hit_tokens - req.prefix_hits_at_drain)
            req.prefix_hits_at_drain = req.prefix_hit_tokens
            if rebase_for_recompute(req):
                req.state = RequestState.QUEUED
                req.requeued = True
                req.redispatches += 1
                moved.append(req)
            else:
                # Killed after its last token was emitted but before
                # the bookkeeping finished it: nothing left to
                # generate — finish, never re-emit (at-most-once).
                req.state = RequestState.FINISHED
                req.t_finish = now
                if req.t_admit is not None:
                    # same service-time sample _collect would stamp —
                    # incident-affected requests must not vanish from
                    # the retry-after estimate.
                    self._service_samples.append(now - req.t_admit)
                self.finished.append(req)
        rep.assigned = []
        self.queue[0:0] = moved
        self.redispatched_total += len(moved)
        self.tokens_recomputed_total += recomputed
        return len(moved), recomputed

    def _check_watchdog(self, now: float) -> None:
        # Transport-liveness lane (tcp replicas): the router cannot
        # stat a remote heartbeat FILE, so liveness is the worker's
        # heartbeat SEQUENCE riding every step/ping/collect reply,
        # aged by the ROUTER's clock. A wedged engine thread keeps its
        # RPC control thread answering — with a frozen sequence — so
        # the stale age here is exactly what the stale file mtime is
        # for local replicas: the silent-stall signal, classified
        # ``stalled``.
        if self.fleet.watchdog_timeout > 0:
            for rep in self.replicas:
                if not rep.healthy or rep.liveness != "transport":
                    continue
                age = now - (rep.hb_at if rep.hb_at is not None
                             else self._t_start)
                if age > self.fleet.watchdog_timeout:
                    _log(f"health watchdog: replica {rep.id} transport "
                         f"heartbeat stale for {age:.2f}s (timeout "
                         f"{self.fleet.watchdog_timeout:g}s) — killing "
                         "the stalled replica")
                    self._kill_replica(rep, code=-int(_signal.SIGKILL),
                                       stalled=True, now=now,
                                       detect_age=age)
        if self.watchdog is None:
            return
        live = [r.id for r in self.replicas
                if r.healthy and r.liveness == "file"]
        for rid, age in self.watchdog.check(live).items():
            rep = self.replicas[rid]
            self.watchdog.kills[rid] = age
            _log(f"health watchdog: replica {rid} heartbeat stale for "
                 f"{age:.2f}s (timeout {self.watchdog.timeout:g}s) — "
                 "killing the stalled replica")
            self._kill_replica(rep, code=-int(_signal.SIGKILL),
                               stalled=True, now=now, detect_age=age)

    def _relaunch_due(self, now: float) -> None:
        for rep in self.replicas:
            if rep.state != "dead" or now < rep.relaunch_at:
                continue
            if self.restarts_used >= self.fleet.max_restarts:
                rep.state = "failed"
                _log(f"replica {rep.id}: restart budget exhausted "
                     f"({self.restarts_used}/{self.fleet.max_restarts} "
                     "used) — marking failed; the fleet degrades")
                continue
            self.restarts_used += 1
            rep.restarts += 1
            fresh = self._spawn(rep.id)
            rep.adopt(fresh)
            rep.state = "healthy"
            rep.exit = None
            if rep.liveness == "transport":
                # Fresh incarnation, fresh liveness grace (the spawn
                # stamped fresh.hb_at; the adopted replica keeps its
                # identity but must not inherit a stale age).
                rep.hb_seq = None
                rep.hb_at = fresh.hb_at
            if self.watchdog is not None:
                # The PREVIOUS incarnation's kill record must not mute
                # watching the fresh one.
                self.watchdog.kills.pop(rep.id, None)
            _log(f"replica {rep.id} relaunched (attempt {rep.restarts}; "
                 f"{self.fleet.max_restarts - self.restarts_used} "
                 "restart(s) left fleet-wide)")
        if not self.alive and self.queue:
            # Zero replicas left, forever: shed the backlog instead of
            # holding clients in a queue that can never drain.
            _log(f"all replicas failed — shedding {len(self.queue)} "
                 "queued request(s)")
            for req in self.queue:
                self._reject(req, "overloaded")
            self.queue = []

    # ------------------------------------------------------- dispatch

    def _expire_queued(self, now: float) -> None:
        """Router-level TTL sweep: a request can blow its deadline
        waiting in the FLEET queue (each engine sweeps its own)."""
        expired = [r for r in self.queue if r.expired(now)]
        if not expired:
            return
        self.queue = [r for r in self.queue if not r.expired(now)]
        for req in expired:
            req.state = RequestState.TIMEOUT
            req.t_finish = now
            self.timed_out.append(req)

    def _version_stranded(self, req: Request) -> bool:
        """A pinned request whose params version no replica can EVER
        serve again: relaunches always wire-init from the CURRENT
        artifact, so a version older than ``params_version`` survives
        only on still-healthy replicas — none left means waiting at
        the head would strand the request forever."""
        return (req.version is not None
                and req.version != self.params_version
                and not any(r.healthy and r.version == req.version
                            for r in self.replicas))

    def _route_key(self, req: Request) -> Optional[str]:
        """The request's prefix-affinity key (None = no affinity /
        prefix caching off). First-chunk hashing makes the key stable
        under :func:`rebase_for_recompute` — a redispatched request
        rendezvouses onto the same survivor as its prefix-mates."""
        if not self.config.prefix_caching:
            return None
        from horovod_tpu.serve.prefix import prefix_route_key

        return prefix_route_key(req.prompt, self.config.page_size)

    def _dispatch(self) -> None:
        # Disaggregated pools: every admission (fresh or requeued — a
        # rebased request needs its folded prompt re-prefilled) goes
        # to the PREFILL pool only; decode-pool slots are never
        # consumed by admission, and the decode side receives work
        # exclusively through the KV handoff (serve/disagg.py).
        pool = self.replicas if self.disagg is None else \
            self.disagg.prefill_pool()
        while self.queue:
            req = self.queue[0]
            rep = pick_replica(pool, req, self._route_key(req))
            if rep is None:
                if self._version_stranded(req):
                    # The explicit cross-version policy: the stream
                    # RESTARTS from its original prompt under the new
                    # version (scheduler.restart_from_scratch) — the
                    # rebase alternative would splice tokens from two
                    # different models into one stream.
                    _log(f"request {req.rid}: pinned params v"
                         f"{req.version} can never be served again — "
                         "restarting the stream from scratch under "
                         f"v{self.params_version} (explicit policy; "
                         f"{len(req.output)} emitted token(s) "
                         "retracted as a stream restart)")
                    restart_from_scratch(req)
                    self.version_recomputed += 1
                    continue
                break   # head waits; order (and requeue priority) holds
            self.queue.pop(0)
            # Stamped per DISPATCH, not per request: the same request
            # redispatched after a decode-side death prefills again on
            # the prefill pool; colocated fleets always stamp False.
            req.prefill_only = self.disagg is not None
            try:
                accepted = rep.engine.scheduler.submit(req)
            except TransportError as e:
                # The request never reached the replica (or we cannot
                # know that it did — same thing under at-most-once: it
                # was never ACKed, so it is safe to hand to a
                # survivor). Back to the head, replica into the death
                # path, keep dispatching.
                self.queue.insert(0, req)
                req.state = RequestState.QUEUED
                self._transport_death(rep, e, self.clock())
                continue
            if not accepted:
                # Defensive only: eligible() mirrors every admission
                # check (geometry, in-flight headroom, the engine's own
                # bounded queue), so a failure here means drift the
                # router could not see. The engine already stamped the
                # reject and listed it — move that ONE record to the
                # fleet list (never both: stats must not double-count).
                if req in rep.engine.scheduler.rejected:
                    rep.engine.scheduler.rejected.remove(req)
                self.rejected.append(req)
                if req.reject_reason == "overloaded":
                    self.shed_total += 1
                continue
            rep.assigned.append(req)
            req.replica = rep.id
            if req.version is None:
                # First dispatch pins the request's ENTIRE decode to
                # this replica's params version — redispatch may only
                # rebase onto the same version (router.eligible).
                req.version = rep.version

    def _collect(self, rep: Replica) -> None:
        """Pull terminal requests out of a live replica into the fleet
        lists and release router bookkeeping."""
        eng = rep.engine
        done: List[Request] = []
        if eng.finished:
            for req in eng.finished:
                if req.t_finish is not None and req.t_admit is not None:
                    self._service_samples.append(
                        req.t_finish - req.t_admit)
            self.finished.extend(eng.finished)
            done.extend(eng.finished)
            eng.finished = []
        if eng.timed_out:
            self.timed_out.extend(eng.timed_out)
            done.extend(eng.timed_out)
            eng.timed_out = []
        if eng.evicted:
            self.evicted.extend(eng.evicted)
            done.extend(eng.evicted)
            eng.evicted = []
        if eng.scheduler.rejected:
            self.rejected.extend(eng.scheduler.rejected)
            done.extend(eng.scheduler.rejected)
            eng.scheduler.rejected = []
        if done:
            gone = set(id(r) for r in done)
            rep.assigned = [r for r in rep.assigned
                            if id(r) not in gone]

    # ------------------------------------------------------------ step

    def step(self) -> bool:
        """One fleet tick: inject due faults, run the watchdog, process
        due relaunches, wire-init fresh workers, advance a rolling
        update, expire queued deadlines, dispatch, then step every
        live replica once. Returns whether any replica made progress
        (False = idle, everything stalled, or everything waiting on a
        backoff — callers let wall time pass)."""
        if self._closed:
            raise RuntimeError("step() on a closed ServeFleet")
        now = self.clock()
        if self._fault_t0 is None:
            self._fault_t0 = now
        self._inject_faults(now)
        self._check_watchdog(now)
        self._relaunch_due(now)
        self._init_due(now)
        self._advance_update(now)
        self._expire_queued(now)
        self._dispatch()

        progressed = False
        occ: List[float] = []
        ticked: List[Replica] = []
        for rep in self.replicas:
            if not rep.healthy or rep.version is None:
                # version None = wire init still pending (its push
                # failed this tick and the death path is scheduled):
                # the proxy's step RPC would only park on the missing
                # engine.
                continue
            if rep.stall_until is not None:
                if now < rep.stall_until:
                    continue   # no step, no heartbeat: a silent stall
                rep.stall_until = None
            t0 = self.clock()
            try:
                stepped = rep.engine.step()
            except TransportError as e:
                # The wire to a process worker failed (torn frame from
                # a kill mid-write, deadline expiry, connection lost):
                # replica death, by the tentpole rule. Caught BEFORE
                # the generic handler so the incident records the
                # transport evidence and the real reaped exit code.
                self._transport_death(rep, e, now)
                continue
            except Exception as e:
                # A REAL replica crash (engine bug, allocator error,
                # device OOM) — the docstring's contract: one replica
                # is one failure domain. Classify + drain + relaunch
                # like any kill; never let it abort the fleet loop.
                import traceback

                _log(f"replica {rep.id} raised "
                     f"{type(e).__name__}: {e} — classifying as a "
                     "crash\n" + traceback.format_exc())
                self._kill_replica(rep, code=1, stalled=False, now=now)
                continue
            if stepped:
                progressed = True
                rep.steps += 1
                if rep.slow_factor > 1.0:
                    dt = self.clock() - t0
                    if dt > 0:
                        self._sleep((rep.slow_factor - 1.0) * dt)
            if rep.liveness == "transport":
                # Age the transport liveness channel with the ROUTER's
                # clock: the sequence moving (the worker's engine loop
                # iterated, idle ticks included) is what freshness
                # means — reply arrival alone is only the RPC thread.
                # Stamp the clock NOW, not the tick-top `now`: one
                # slow peer step earlier in this tick (a relaunch
                # compile) must not age a healthy, advancing replica's
                # stamp toward a spurious stall kill — the same
                # discipline the end-of-tick file stamping below
                # exists for.
                hb = getattr(rep.engine, "last_hb", None)
                if hb is not None and hb != rep.hb_seq:
                    rep.hb_seq = hb
                    rep.hb_at = self.clock()
            ticked.append(rep)
            self._collect(rep)
            occ.append(rep.engine.cache.occupancy())
        if self.disagg is not None:
            # KV handoffs AFTER every replica stepped (the handoff
            # snapshots are this tick's truth): a completed transfer
            # is fleet progress even when no engine generated.
            if self.disagg.step(now):
                progressed = True
        # Heartbeats stamp at the END of the tick, together: replicas
        # step sequentially in-process, so stamping each inside the
        # loop would let one slow step (a fresh replica's compile) age
        # every PEER's file past the watchdog timeout — a spurious
        # "stalled" kill of a healthy replica. End-of-tick stamping
        # means the next check (top of the following tick) sees ~zero
        # age for every replica that completed this tick; only
        # genuinely skipped replicas — stalled or dead — go stale. An
        # idle-but-healthy replica still stamps (engine.step() False is
        # "nothing to do", not "wedged"). Process workers stamp their
        # OWN file per served tick — the fleet must never stamp for
        # them, or a wedged worker would look alive forever.
        for rep in ticked:
            if not rep.stamps_own_heartbeat:
                rep.heartbeat.touch(rep.steps)
        if occ:
            self.occupancy_samples.append(sum(occ) / len(occ))
        self.steps += 1
        return progressed

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drain to idle (or ``max_steps`` fleet ticks); returns
        requests finished so far. Ticks that make no progress (a stall
        waiting for the watchdog, a relaunch waiting out its backoff)
        sleep briefly so wall time — which heartbeat mtimes and
        backoffs are measured in — actually passes. An in-progress
        rolling update keeps the loop alive past request-idle: the
        roll must complete (every replica on the new version) before
        the fleet is done."""
        while not self.idle or self._update is not None:
            if max_steps is not None and self.steps >= max_steps:
                break
            if not self.step():
                if self.idle and self._update is None:
                    break
                self._sleep(0.001)
        return self.finished

    # ---------------------------------------------------------- stats

    def reset_metrics(self) -> None:
        """Bench warmup discipline (compile+warm every replica, then
        measure from a clean slate). Only valid when idle; replica
        health/restart state survives (a mid-life reset must not
        forget a failed replica)."""
        if not self.idle:
            raise RuntimeError("reset_metrics with requests in flight")
        self.finished = []
        self.timed_out = []
        self.evicted = []
        self.rejected = []
        self._service_samples.clear()
        self.incidents = []
        self.incidents_by_class = {}
        self.redispatched_total = 0
        self.tokens_recomputed_total = 0
        self.redispatch_prefix_saved = 0
        self.shed_total = 0
        self.occupancy_samples = []
        self.steps = 0
        self._rpc_samples.clear()
        self.transport_incidents = {}
        self.push_stats = {"pushes": 0, "bytes": 0, "chunks": 0,
                           "retries": 0, "ms": 0.0}
        self.transfer_incidents = {}
        self.version_recomputed = 0
        if self.disagg is not None:
            self.disagg.reset_metrics()
        for rep in self.replicas:
            if rep.healthy and rep.engine is not None:
                try:
                    rep.engine.reset_metrics()
                except TransportError as e:
                    # A reset is the one RPC issued outside step();
                    # the death rule is the same (the replica will be
                    # relaunched with fresh metrics anyway).
                    self._transport_death(rep, e, self.clock())
                    continue
                rep.steps = 0
        self._fault_t0 = None
        self._t_start = self.clock()

    def stats(self) -> Dict:
        """SLO metrics over every request seen, plus the ``fleet``
        block: per-replica occupancy/health, rejection/timeout/
        redispatch counts, classified incidents, and
        detection/recovery evidence (the router-level satellite of
        ROADMAP's "serve-engine TTL/SLO metrics in the fleet
        router")."""
        from horovod_tpu.serve.metrics import summarize

        in_service = [r for rep in self.replicas for r in rep.assigned]
        everything = (self.finished + self.timed_out + self.evicted
                      + self.rejected + list(self.queue) + in_service)
        out = summarize(everything, self.clock() - self._t_start,
                        self.chips, self.occupancy_samples)
        by_reason: Dict[str, int] = {}
        for req in self.rejected:
            key = req.reject_reason or "?"
            by_reason[key] = by_reason.get(key, 0) + 1
        detect = [i["detect_s"] for i in self.incidents
                  if i["category"] == "stalled"]
        from horovod_tpu.serve.metrics import percentile

        rpc_ms = None
        if self.fleet.transport in ("process", "tcp"):
            s = self._rpc_samples
            rpc_ms = {
                "calls": len(s),
                "p50": round(percentile(s, 50), 4) if s else None,
                "p99": round(percentile(s, 99), 4) if s else None,
            }
        # Fleet-level prefix accounting off ROUTER bookkeeping (the
        # per-request stamps), so one code path covers every transport
        # — inproc engines and wire workers alike. ``tokens_saved``
        # that landed PAST a drain baseline were part of the
        # pessimistic drain-time recompute count and net out of the
        # reported ``tokens_recomputed``.
        prefix_block = None
        recomputed_net = self.tokens_recomputed_total
        if self.config.prefix_caching:
            admitted = [r for r in everything if r.t_admit is not None]
            hits = sum(1 for r in admitted if r.prefix_hit_tokens > 0)
            live_saved = sum(
                max(0, r.prefix_hit_tokens - r.prefix_hits_at_drain)
                for r in everything
                if r.prefix_hits_at_drain is not None)
            redispatch_saved = self.redispatch_prefix_saved + live_saved
            prefix_block = {
                "requests": len(admitted),
                "hits": hits,
                "hit_rate": round(hits / len(admitted), 4)
                if admitted else None,
                "prefill_tokens_saved": sum(
                    r.prefix_hit_tokens for r in admitted),
                "pages_shared": sum(
                    r.prefix_hit_pages for r in admitted),
                "redispatch_tokens_saved": redispatch_saved,
            }
            recomputed_net = max(
                0, self.tokens_recomputed_total - redispatch_saved)
        out["fleet"] = {
            "replicas": len(self.replicas),
            "transport": self.fleet.transport,
            "hosts": len(self._hosts) or None,
            "host_incidents": sum(
                1 for i in self.incidents
                if i.get("category") == "host_down"),
            "rpc_ms": rpc_ms,
            "transport_incidents": dict(self.transport_incidents),
            "params_version": self.params_version,
            "params_push": dict(self.push_stats,
                                version=self.params_version),
            "transfer_incidents": dict(self.transfer_incidents),
            "version_recomputed": self.version_recomputed,
            "update_active": self._update is not None,
            "healthy": sum(1 for r in self.replicas if r.healthy),
            "dead": sum(1 for r in self.replicas if r.state == "dead"),
            "failed": sum(1 for r in self.replicas
                          if r.state == "failed"),
            "queued": len(self.queue),
            "redispatched": self.redispatched_total,
            "tokens_recomputed": recomputed_net,
            # the pessimistic drain-time count, before netting out the
            # survivors' prefix hits (equal unless prefix caching is on
            # and a redispatched request re-matched on its survivor)
            "tokens_recomputed_raw": self.tokens_recomputed_total,
            "prefix": prefix_block,
            "shed": self.shed_total,
            "rejected_by_reason": by_reason,
            "timeout": len(self.timed_out),
            "incidents": list(self.incidents),
            "incidents_by_class": dict(self.incidents_by_class),
            "restarts_used": self.restarts_used,
            "max_restarts": self.fleet.max_restarts,
            "detect_s": round(max(detect), 4) if detect else None,
            "disagg": self.disagg.stats()
            if self.disagg is not None else None,
            "per_replica": [
                dict(replica_load(r), id=r.id, state=r.state,
                     role=r.role, steps=r.steps, restarts=r.restarts,
                     version=r.version, params_sha=r.params_sha)
                for r in self.replicas],
        }
        return out
