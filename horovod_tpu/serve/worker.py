"""Replica worker process: one ServeEngine behind the fleet transport.

``python -m horovod_tpu.serve.worker --socket S --params P --config C
--rank R --heartbeat-dir D`` runs ONE
:class:`~horovod_tpu.serve.engine.ServeEngine` as its own OS process —
the crash-isolation boundary the in-process fleet honestly lacked: a
replica that segfaults, OOMs, or is SIGKILLed takes down exactly one
worker, never the router or its peers. ``--bind host:port`` (instead
of ``--socket``) serves the same frame protocol over TCP — the
multi-host placement: the listener demands the fleet's shared secret
(``HOROVOD_SECRET``; every accepted connection passes the HMAC
handshake before an RPC is served), liveness rides a heartbeat
SEQUENCE in every ping/step/collect reply instead of a file the
router could not see, and the advertised endpoint resolves through
``run/network.py``'s offline-safe fallback chain.

Two threads, one failure story:

* the **engine loop** (main thread) steps the engine whenever it has
  work, harvests terminal requests into the collect outbox, and
  touches the replica's heartbeat file at the END of each served tick
  (idle ticks included — ``step() == False`` is "nothing to do", not
  "wedged") — exactly the PR-12 liveness contract, now fed by a real
  process so a ``stall:`` fault genuinely wedges this thread and ONLY
  the stale heartbeat + the supervisor-side
  :class:`~horovod_tpu.elastic.supervisor.HealthWatchdog` can catch it;
* the **RPC thread** serves the router's calls (``submit`` / ``step`` /
  ``collect`` / ``stats`` / ``drain`` / ``reset_metrics`` / ``fault`` /
  ``shutdown`` / ``ping``) over the framed Unix-socket protocol
  (:mod:`~horovod_tpu.serve.transport`), sharing the engine under one
  lock. It stays responsive through an engine-loop stall — which is
  what routes a wedged replica to the watchdog (``stalled``) instead of
  an RPC deadline (``crashed``): the control plane answers, the data
  plane is silent.

The socket is bound BEFORE the heavy jax import so the router's
connect succeeds early; the first RPCs then wait (inside their
deadline) for engine construction. A worker that dies during startup
never binds, never heartbeats — the router observes the connect
failure plus the reaped exit code and classifies ``crashed`` through
the PR-9 taxonomy (it consumes restart budget; see
docs/troubleshooting.md).

Timestamps: the router stamps every request's latency trail with its
OWN clock at collect time (what a streaming client at the router
actually observes) — worker-side clock stamps never cross the process
boundary, so there is no cross-process clock skew to reconcile.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from horovod_tpu.run.driver import EXIT_CLEAN, EXIT_USAGE
from horovod_tpu.serve.transport import serve_connection

# ------------------------------------------------------------------ params

_LEAF = "__leaf_{}__"


def save_params(params, path: str) -> None:
    """Serialize a dict/list pytree of arrays to one ``.npz`` (a JSON
    structure spec plus one entry per leaf) — the fleet writes it once,
    every worker incarnation loads it, so all replicas decode with
    BIT-IDENTICAL weights (the redispatch exactness pin depends on
    it)."""
    leaves: List[np.ndarray] = []

    def enc(x):
        if isinstance(x, dict):
            return {k: enc(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [enc(v) for v in x]
        leaves.append(np.asarray(x))
        return _LEAF.format(len(leaves) - 1)

    spec = enc(params)
    np.savez(path, __spec__=np.asarray(json.dumps(spec)),
             **{f"leaf_{i}": a for i, a in enumerate(leaves)})


def load_params(path: str, as_jax: bool = True):
    """Inverse of :func:`save_params`; ``as_jax`` converts leaves once
    so the engine's compiled steps don't re-upload host arrays every
    call."""
    with np.load(path, allow_pickle=False) as z:
        spec = json.loads(str(z["__spec__"]))
        leaves = {f"leaf_{i}": z[f"leaf_{i}"]
                  for i in range(len(z.files) - 1)}
    if as_jax:
        import jax.numpy as jnp

        leaves = {k: jnp.asarray(v) for k, v in leaves.items()}

    def dec(x):
        if isinstance(x, dict):
            return {k: dec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [dec(v) for v in x]
        if isinstance(x, str) and x.startswith("__leaf_") \
                and x.endswith("__"):
            return leaves[f"leaf_{int(x[7:-2])}"]
        return x

    return dec(spec)


def _jsonable(x: Any) -> Any:
    """Stats payloads -> JSON-safe (numpy scalars/arrays demoted)."""
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    return x


# ------------------------------------------------------------------- host


class WorkerHost:
    """The worker's two-thread engine host (see module docstring).

    ``secret`` (TCP placement) arms the shared-secret connect
    handshake: every accepted connection must answer the HMAC
    challenge before a single RPC frame is served — a TCP listener is
    network-reachable, unlike the filesystem-gated Unix socket."""

    def __init__(self, engine, heartbeat=None, secret=None):
        self.engine = engine
        self.heartbeat = heartbeat
        self._secret = secret
        #: Transport liveness channel: bumped once per engine-loop
        #: iteration (idle ticks included — "nothing to do" is not
        #: "wedged"), reported in every ping/step/collect reply so a
        #: router that cannot see this machine's heartbeat FILE can
        #: age the same signal off the wire.
        self._hb_seq = 0
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        #: router rid -> the ENGINE's Request (the worker's own rids
        #: never cross the wire).
        self._requests: Dict[int, Any] = {}
        self._terminal: List[Dict] = []
        self._ticks = 0
        self._stall_pending: Optional[Dict] = None
        self._slow = 1.0
        self._collects = 0
        self._last_hb = 0.0
        torn = os.environ.get("HVD_SERVE_WORKER_TORN_COLLECT_AFTER")
        #: test hook: after N collect responses, write HALF the next
        #: collect reply frame and die — the deterministic
        #: kill-mid-write shape the codec/fuzz pin exercises e2e.
        self._torn_after = int(torn) if torn else None

    # ------------------------------------------------- engine loop

    def serve_loop(self) -> None:
        while not self._shutdown.is_set():
            with self._lock:
                stall, self._stall_pending = self._stall_pending, None
            if stall is not None:
                secs = stall.get("secs")
                if secs is None:
                    # A genuine wedge: the engine thread stops stepping
                    # and stops heartbeating, forever. Only SIGKILL (the
                    # watchdog's, or close()'s escalation) — or an
                    # explicit shutdown RPC — ends it.
                    while not self._shutdown.is_set():
                        time.sleep(1.0)
                    break
                time.sleep(float(secs))
            t0 = time.perf_counter()
            with self._lock:
                progressed = self.engine.step()
                if progressed:
                    self._ticks += 1
                self._harvest_locked()
            self._hb_seq += 1
            if progressed and self._slow > 1.0:
                dt = time.perf_counter() - t0
                if dt > 0:
                    time.sleep((self._slow - 1.0) * dt)
            if self.heartbeat is not None:
                # END of the served tick (idle ones included): the
                # PR-12 liveness cadence, stamped by the worker
                # itself — rate-limited to 50 ms so a fast/idle loop
                # is not ~500 file writes/s for zero information (the
                # watchdog only needs sub-timeout freshness; a long
                # tick, e.g. a compile, always ends with a touch).
                now = time.monotonic()
                if now - self._last_hb >= 0.05:
                    self.heartbeat.touch(self._ticks)
                    self._last_hb = now
            if not progressed:
                time.sleep(0.002)

    def _harvest_locked(self) -> None:
        eng = self.engine
        for lst in (eng.finished, eng.timed_out, eng.evicted,
                    eng.scheduler.rejected):
            for req in lst:
                rid = getattr(req, "_router_rid", None)
                if rid is None:
                    continue   # not router-owned (defensive)
                self._terminal.append(self._serialize(rid, req))
                self._requests.pop(rid, None)
            lst.clear()

    @staticmethod
    def _serialize(rid: int, req) -> Dict:
        return {
            "rid": int(rid),
            "state": req.state,
            "output": [int(t) for t in req.output],
            "prefill_pos": int(req.prefill_pos),
            "generated_len": len(req.generated),
            "evictions": int(req.evictions),
            "reject_reason": req.reject_reason,
            "retry_after": req.retry_after,
        }

    # -------------------------------------------------- RPC thread

    def handle(self, method: str, params: Dict) -> Any:
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None or not method:
            raise ValueError(f"unknown RPC method {method!r}")
        return fn(params)

    def _rpc_ping(self, p: Dict) -> Dict:
        return {"pid": os.getpid(), "ticks": self._ticks,
                "hb": self._hb_seq}

    def _rpc_submit(self, p: Dict) -> Dict:
        from horovod_tpu.serve.scheduler import make_request

        with self._lock:
            eng = self.engine
            req = make_request(
                eng.config, eng.clock,
                np.asarray(p["prompt"], np.int32),
                int(p["max_new_tokens"]),
                temperature=float(p.get("temperature", 0.0)),
                top_k=int(p.get("top_k", 0)),
                eos_token=p.get("eos_token"),
                seed=int(p.get("seed", 0)),
                # reconstruct arrival in THIS process's clock so the
                # engine-side TTL sweep keeps the original deadline
                arrival=eng.clock() - float(p.get("age", 0.0)),
                ttl=p.get("ttl"))
            req._router_rid = int(p["rid"])
            if eng.scheduler.submit(req):
                self._requests[int(p["rid"])] = req
                return {"accepted": True}
            # engine stamped the reject; report it inline (never also
            # via the outbox — the router owns the single record)
            if req in eng.scheduler.rejected:
                eng.scheduler.rejected.remove(req)
            return {"accepted": False,
                    "reject_reason": req.reject_reason,
                    "retry_after": req.retry_after}

    def _rpc_step(self, p: Dict) -> Dict:
        with self._lock:
            eng = self.engine
            return {"ticks": self._ticks,
                    "hb": self._hb_seq,
                    "free_slots": eng._free_slots(),
                    "occupancy": float(eng.cache.occupancy()),
                    "queue_len": len(eng.scheduler.queue),
                    "in_flight": eng.in_flight,
                    "idle": eng.idle}

    def _rpc_collect(self, p: Dict) -> Dict:
        since = p.get("since") or {}
        with self._lock:
            self._harvest_locked()
            events, self._terminal = self._terminal, []
            progress = []
            for rid_s, n in since.items():
                req = self._requests.get(int(rid_s))
                if req is None:
                    continue   # terminal event already covers it
                progress.append({
                    "rid": int(rid_s),
                    "tokens": [int(t) for t in req.output[int(n):]],
                    "prefill_pos": int(req.prefill_pos),
                    "generated_len": len(req.generated),
                })
        self._collects += 1
        return {"events": events, "progress": progress,
                "hb": self._hb_seq}

    def _rpc_stats(self, p: Dict) -> Dict:
        with self._lock:
            return _jsonable(self.engine.stats())

    def _rpc_drain(self, p: Dict) -> Dict:
        deadline = time.monotonic() + float(p.get("timeout", 5.0))
        while time.monotonic() < deadline:
            with self._lock:
                if self.engine.idle:
                    return {"idle": True}
            time.sleep(0.005)
        return {"idle": False}

    def _rpc_reset_metrics(self, p: Dict) -> Dict:
        with self._lock:
            self.engine.reset_metrics()   # raises if not idle
            self._ticks = 0
        return {"ticks": 0}

    def _rpc_fault(self, p: Dict) -> Dict:
        kind = p.get("kind")
        with self._lock:
            if kind == "stall":
                self._stall_pending = {"secs": p.get("secs")}
            elif kind == "slow":
                self._slow = float(p["factor"])
            else:
                raise ValueError(f"unknown fault kind {kind!r} (the "
                                 "kill edition is a real signal)")
        return {}

    def _rpc_shutdown(self, p: Dict) -> Dict:
        self._shutdown.set()
        # The engine thread may be genuinely wedged (a bounded stall
        # mid-sleep): guarantee exit shortly after the reply flushes,
        # through the taxonomy's clean code either way.
        timer = threading.Timer(0.5, os._exit, args=(EXIT_CLEAN,))
        timer.daemon = True
        timer.start()
        return {"pid": os.getpid()}

    # ---------------------------------------------- plumbing

    def _send_hook(self, sock: socket.socket, frame: bytes) -> bool:
        if self._torn_after is not None \
                and self._collects >= self._torn_after:
            sock.settimeout(5.0)
            sock.sendall(frame[:max(1, len(frame) // 2)])
            os._exit(1)   # die mid-write: the torn-frame crash shape
        return False

    def rpc_loop(self, server_sock: socket.socket) -> None:
        from horovod_tpu.serve.transport import server_handshake

        while not self._shutdown.is_set():
            server_sock.settimeout(0.25)
            try:
                conn, _ = server_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                if self._secret:
                    # TCP listener: anything that routes to the port
                    # can connect — prove the fleet secret before a
                    # single RPC frame is served, drop otherwise.
                    if not server_handshake(
                            conn, self._secret,
                            time.monotonic() + 5.0):
                        continue
                serve_connection(conn, self.handle,
                                 should_stop=self._shutdown.is_set,
                                 send_hook=self._send_hook)


# ------------------------------------------------------------------- main


def main(argv=None) -> int:
    # Startup-failure test hook: before ANY heavy work, so the fleet
    # sees a worker that dies pre-bind, pre-heartbeat (classified
    # crashed, consumes restart budget — docs/troubleshooting.md).
    fail = os.environ.get("HVD_SERVE_WORKER_FAIL_START")
    if fail:
        print("serve.worker: HVD_SERVE_WORKER_FAIL_START set — "
              "exiting before startup", file=sys.stderr, flush=True)
        return int(fail)

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve.worker",
        description="One serving-fleet replica worker process.")
    ap.add_argument("--socket", default="",
                    help="Unix-domain socket path to serve RPCs on "
                         "(the same-host 'process' transport)")
    ap.add_argument("--bind", default="",
                    help="TCP 'host:port' to listen on instead of a "
                         "unix socket (the multi-host 'tcp' "
                         "transport; port 0 = ephemeral). Requires "
                         "HOROVOD_SECRET in the environment — a TCP "
                         "listener is network-reachable, so every "
                         "connection must pass the shared-secret "
                         "handshake")
    ap.add_argument("--params", required=True,
                    help="npz of model params (worker.save_params)")
    ap.add_argument("--config", required=True,
                    help="path to the ServeConfig JSON")
    ap.add_argument("--rank", type=int, default=0,
                    help="replica id (heartbeat file + logs)")
    ap.add_argument("--heartbeat-dir", default="",
                    help="fleet heartbeat directory ('' = no beacon; "
                         "tcp workers normally run without one — "
                         "liveness rides the transport)")
    args = ap.parse_args(argv)
    if bool(args.socket) == bool(args.bind):
        ap.error("exactly one of --socket (unix) or --bind host:port "
                 "(tcp) is required")

    # Bind BEFORE the heavy init: the router's connect succeeds as soon
    # as the process is alive; its first RPCs wait inside their own
    # deadline for the engine to finish constructing.
    secret = ""
    if args.bind:
        host, _, port_s = args.bind.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            print(f"serve.worker[{args.rank}]: --bind {args.bind!r} is "
                  "not host:port", file=sys.stderr, flush=True)
            return EXIT_USAGE
        secret = os.environ.get("HOROVOD_SECRET", "")
        if not secret:
            print(f"serve.worker[{args.rank}]: --bind needs "
                  "HOROVOD_SECRET in the environment — refusing to "
                  "serve an unauthenticated network listener",
                  file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host or "0.0.0.0", port))
        except OSError as e:
            print(f"serve.worker[{args.rank}]: cannot bind "
                  f"{args.bind}: {e}", file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv.listen(2)
        bound_port = srv.getsockname()[1]
        # Advertised-address resolution (run/network.py's offline-safe
        # fallback chain): which endpoint peers should dial when the
        # bind address is a wildcard.
        from horovod_tpu.run.network import advertise_ip

        adv = host if host and host != "0.0.0.0" else advertise_ip()
        print(f"serve.worker[{args.rank}]: tcp listener on "
              f"{args.bind} (advertise {adv}:{bound_port})",
              file=sys.stderr, flush=True)
    else:
        try:
            os.unlink(args.socket)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(args.socket)
        except OSError as e:
            print(f"serve.worker[{args.rank}]: cannot bind "
                  f"{args.socket}: {e}", file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv.listen(2)

    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        # This image's sitecustomize imports jax at interpreter startup
        # (the conftest note): config.update is the reliable override.
        jax.config.update("jax_platforms", plat.split(",")[0])

    from horovod_tpu.elastic.signals import Heartbeat
    from horovod_tpu.serve.config import ServeConfig
    from horovod_tpu.serve.engine import ServeEngine

    with open(args.config) as f:
        cfg = ServeConfig(**json.load(f))
    params = load_params(args.params)
    engine = ServeEngine(params, cfg)
    hb = Heartbeat(args.heartbeat_dir, rank=args.rank) \
        if args.heartbeat_dir else None

    host_loop = WorkerHost(engine, hb, secret=secret or None)
    rpc = threading.Thread(target=host_loop.rpc_loop, args=(srv,),
                           daemon=True,
                           name=f"serve-worker-rpc-{args.rank}")
    rpc.start()
    print(f"serve.worker[{args.rank}]: serving on "
          f"{args.bind or args.socket} (pid {os.getpid()})",
          file=sys.stderr, flush=True)
    host_loop.serve_loop()
    srv.close()
    return EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
