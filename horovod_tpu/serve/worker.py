"""Replica worker process: one ServeEngine behind the fleet transport.

``python -m horovod_tpu.serve.worker --socket S --rank R
--heartbeat-dir D`` runs ONE
:class:`~horovod_tpu.serve.engine.ServeEngine` as its own OS process —
the crash-isolation boundary the in-process fleet honestly lacked: a
replica that segfaults, OOMs, or is SIGKILLed takes down exactly one
worker, never the router or its peers. ``--bind host:port`` (instead
of ``--socket``) serves the same frame protocol over TCP — the
multi-host placement: the listener demands the fleet's shared secret
(``HOROVOD_SECRET``; every accepted connection passes the HMAC
handshake before an RPC is served), liveness rides a heartbeat
SEQUENCE in every ping/step/collect reply instead of a file the
router could not see, and the advertised endpoint resolves through
``run/network.py``'s offline-safe fallback chain.

**Wire init (the fleet's default).** With no ``--params``/``--config``
the worker starts with NOTHING from any filesystem: it binds, serves
the transfer RPCs (``put_config`` + ``push_begin``/``push_chunk``/
``push_commit`` — :mod:`~horovod_tpu.serve.params_wire`), assembles
the versioned params artifact into its own private temp dir with
per-chunk CRCs, whole-artifact digest verify, and an atomic-rename
commit, and only THEN builds the engine. Every spawn, relaunch, and
redispatch incarnation therefore decodes with bit-identical,
digest-verified weights — no shared-filesystem assumption on any
transport. The same push RPCs later swap weights live (the fleet's
zero-downtime rolling update): the fleet drains this replica first,
``push_commit`` verifies the digest and replaces the idle engine's
params in place. ``--params P --config C`` (both together) remains the
standalone file mode for running a worker by hand.

Two threads, one failure story:

* the **engine loop** (main thread) steps the engine whenever it has
  work, harvests terminal requests into the collect outbox, and
  touches the replica's heartbeat file at the END of each served tick
  (idle ticks included — ``step() == False`` is "nothing to do", not
  "wedged") — exactly the PR-12 liveness contract, now fed by a real
  process so a ``stall:`` fault genuinely wedges this thread and ONLY
  the stale heartbeat + the supervisor-side
  :class:`~horovod_tpu.elastic.supervisor.HealthWatchdog` can catch it;
* the **RPC thread** serves the router's calls (``submit`` / ``step`` /
  ``collect`` / ``stats`` / ``drain`` / ``reset_metrics`` / ``fault`` /
  ``shutdown`` / ``ping``) over the framed Unix-socket protocol
  (:mod:`~horovod_tpu.serve.transport`), sharing the engine under one
  lock. It stays responsive through an engine-loop stall — which is
  what routes a wedged replica to the watchdog (``stalled``) instead of
  an RPC deadline (``crashed``): the control plane answers, the data
  plane is silent.

The socket is bound BEFORE the heavy jax import so the router's
connect succeeds early; the first RPCs then wait (inside their
deadline) for engine construction. A worker that dies during startup
never binds, never heartbeats — the router observes the connect
failure plus the reaped exit code and classifies ``crashed`` through
the PR-9 taxonomy (it consumes restart budget; see
docs/troubleshooting.md).

Timestamps: the router stamps every request's latency trail with its
OWN clock at collect time (what a streaming client at the router
actually observes) — worker-side clock stamps never cross the process
boundary, so there is no cross-process clock skew to reconcile.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from horovod_tpu.run.driver import EXIT_CLEAN, EXIT_USAGE
from horovod_tpu.serve import params_wire
from horovod_tpu.serve.transport import serve_connection

# ------------------------------------------------------------------ params


def save_params(params, path: str) -> None:
    """Serialize a dict/list pytree of arrays to one deterministic
    artifact file (:func:`params_wire.params_to_blob` — the same
    container the wire transfer ships), committed with tmp + atomic
    rename so a crash mid-write can never leave a torn file a later
    load would parse into silently wrong weights (the HVD012
    discipline)."""
    blob = params_wire.params_to_blob(params)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def load_params(path: str, as_jax: bool = True):
    """Inverse of :func:`save_params`; ``as_jax`` converts leaves once
    so the engine's compiled steps don't re-upload host arrays every
    call."""
    with open(path, "rb") as f:
        blob = f.read()
    return params_wire.params_from_blob(blob, as_jax=as_jax)


def _jsonable(x: Any) -> Any:
    """Stats payloads -> JSON-safe (numpy scalars/arrays demoted)."""
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    return x


# ------------------------------------------------------------------- host


class WorkerHost:
    """The worker's two-thread engine host (see module docstring).

    ``secret`` (TCP placement) arms the shared-secret connect
    handshake: every accepted connection must answer the HMAC
    challenge before a single RPC frame is served — a TCP listener is
    network-reachable, unlike the filesystem-gated Unix socket.

    ``engine`` may be ``None`` (wire init): the RPC thread then serves
    the transfer RPCs immediately — they are pure file I/O against the
    worker's private artifact dir — while the main thread waits for
    config + a digest-verified params artifact before paying the heavy
    jax/engine construction (:meth:`attach_engine`). Engine-facing
    RPCs arriving in that window wait for the engine inside their own
    deadline (the established first-RPC-after-spawn discipline)."""

    def __init__(self, engine, heartbeat=None, secret=None, *,
                 params_version: int = 0,
                 params_sha: Optional[str] = None):
        self.engine = engine
        self.heartbeat = heartbeat
        self._secret = secret
        #: Versioned-weights bookkeeping: which artifact this worker's
        #: engine decodes with (file mode stamps it at startup; wire
        #: init and rolling updates stamp it at push_commit). The sha
        #: is the fleet's digest-verify handle.
        self._params_version = params_version
        self._params_sha = params_sha
        #: Transfer state (wire init + rolling updates).
        self._assembler = None
        self._artifact_dir: Optional[str] = None
        self._pending_config: Optional[Dict] = None
        self._committed_path: Optional[str] = None
        self._engine_ready = threading.Event()
        if engine is not None:
            self._engine_ready.set()
        self._init_ready = threading.Event()
        #: Transport liveness channel: bumped once per engine-loop
        #: iteration (idle ticks included — "nothing to do" is not
        #: "wedged"), reported in every ping/step/collect reply so a
        #: router that cannot see this machine's heartbeat FILE can
        #: age the same signal off the wire.
        self._hb_seq = 0
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        #: router rid -> the ENGINE's Request (the worker's own rids
        #: never cross the wire).
        self._requests: Dict[int, Any] = {}
        #: Disaggregated-serving transfer state, keyed by router rid:
        #: prefill-side senders (exported blob + manifest) and
        #: decode-side receivers (assembler + the pending mirror
        #: Request, engine-admitted only at commit).
        self._kv_senders: Dict[int, Any] = {}
        self._kv_receivers: Dict[int, Any] = {}
        self._terminal: List[Dict] = []
        self._ticks = 0
        self._stall_pending: Optional[Dict] = None
        self._slow = 1.0
        self._collects = 0
        self._last_hb = 0.0
        torn = os.environ.get("HVD_SERVE_WORKER_TORN_COLLECT_AFTER")
        #: test hook: after N collect responses, write HALF the next
        #: collect reply frame and die — the deterministic
        #: kill-mid-write shape the codec/fuzz pin exercises e2e.
        self._torn_after = int(torn) if torn else None

    # ------------------------------------------------- engine loop

    def serve_loop(self) -> None:
        while not self._shutdown.is_set():
            with self._lock:
                stall, self._stall_pending = self._stall_pending, None
            if stall is not None:
                secs = stall.get("secs")
                if secs is None:
                    # A genuine wedge: the engine thread stops stepping
                    # and stops heartbeating, forever. Only SIGKILL (the
                    # watchdog's, or close()'s escalation) — or an
                    # explicit shutdown RPC — ends it.
                    while not self._shutdown.is_set():
                        time.sleep(1.0)
                    break
                time.sleep(float(secs))
            t0 = time.perf_counter()
            with self._lock:
                progressed = self.engine.step()
                if progressed:
                    self._ticks += 1
                self._harvest_locked()
            self._hb_seq += 1
            if progressed and self._slow > 1.0:
                dt = time.perf_counter() - t0
                if dt > 0:
                    time.sleep((self._slow - 1.0) * dt)
            if self.heartbeat is not None:
                # END of the served tick (idle ones included): the
                # PR-12 liveness cadence, stamped by the worker
                # itself — rate-limited to 50 ms so a fast/idle loop
                # is not ~500 file writes/s for zero information (the
                # watchdog only needs sub-timeout freshness; a long
                # tick, e.g. a compile, always ends with a touch).
                now = time.monotonic()
                if now - self._last_hb >= 0.05:
                    self.heartbeat.touch(self._ticks)
                    self._last_hb = now
            if not progressed:
                time.sleep(0.002)

    def _harvest_locked(self) -> None:
        eng = self.engine
        for lst in (eng.finished, eng.timed_out, eng.evicted,
                    eng.scheduler.rejected):
            for req in lst:
                rid = getattr(req, "_router_rid", None)
                if rid is None:
                    continue   # not router-owned (defensive)
                self._terminal.append(self._serialize(rid, req))
                self._requests.pop(rid, None)
            lst.clear()

    @staticmethod
    def _serialize(rid: int, req) -> Dict:
        return {
            "rid": int(rid),
            "state": req.state,
            "output": [int(t) for t in req.output],
            "prefill_pos": int(req.prefill_pos),
            "generated_len": len(req.generated),
            "evictions": int(req.evictions),
            # Prefix-cache stamps (0 when caching is off) — the router
            # mirror needs them for the redispatch-meets-prefix
            # accounting; readers must tolerate their absence (stub
            # workers and pre-prefix workers never send them).
            "prefix_hit_tokens": int(getattr(req, "prefix_hit_tokens",
                                             0)),
            "prefix_hit_pages": int(getattr(req, "prefix_hit_pages",
                                            0)),
            "reject_reason": req.reject_reason,
            "retry_after": req.retry_after,
        }

    # --------------------------------------------------- wire init

    def attach_engine(self, engine, heartbeat=None) -> None:
        """Hand the freshly-built engine to the host (wire init: the
        main thread builds it once config + params have arrived and
        verified). Unblocks every engine-facing RPC waiting in
        :meth:`_require_engine`."""
        self.engine = engine
        if heartbeat is not None:
            self.heartbeat = heartbeat
        self._engine_ready.set()

    def wait_init(self, timeout: float) -> bool:
        """Main-thread wait (wire init) for config + a committed params
        artifact; False on timeout or shutdown-before-init."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._shutdown.is_set():
                return False
            if self._init_ready.wait(0.25):
                return True
        return False

    @property
    def init_config(self) -> Optional[Dict]:
        return self._pending_config

    @property
    def init_params_path(self) -> Optional[str]:
        return self._committed_path

    def _require_engine(self):
        """Engine-facing RPCs block here until the engine exists (the
        wire-init window / the post-spawn jax build). The CALLER's
        deadline is the real bound; this local one only turns a worker
        whose engine can never come up into a typed remote error
        instead of a forever-parked RPC thread."""
        if not self._engine_ready.wait(600.0):
            raise RuntimeError(
                "engine not initialized (no config/params pushed?)")
        return self.engine

    def _ensure_artifact_dir(self) -> str:
        if self._artifact_dir is None:
            # Worker-private, never shared: the whole point of the wire
            # transfer is that no other host/process reads this.
            self._artifact_dir = tempfile.mkdtemp(
                prefix="hvd-worker-params-")
        return self._artifact_dir

    # -------------------------------------------------- RPC thread

    def handle(self, method: str, params: Dict) -> Any:
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None or not method:
            raise ValueError(f"unknown RPC method {method!r}")
        return fn(params)

    def _rpc_ping(self, p: Dict) -> Dict:
        return {"pid": os.getpid(), "ticks": self._ticks,
                "hb": self._hb_seq,
                "params_version": self._params_version or None,
                "params_sha256": self._params_sha}

    # ------------------------------------------- transfer RPCs
    #
    # put_config + push_begin/push_chunk/push_commit: the wire-native
    # weight-distribution lane (serve/params_wire.py). These are the
    # ONLY RPCs the fleet may retry after a TransportError — chunk
    # writes are idempotent (same bytes at the same offset, contiguity
    # enforced, whole-artifact digest at commit), unlike submit.

    def _rpc_put_config(self, p: Dict) -> Dict:
        cfg = p.get("config")
        if not isinstance(cfg, dict):
            raise ValueError(f"put_config: expected a config mapping, "
                             f"got {type(cfg).__name__}")
        if self._engine_ready.is_set():
            if self._pending_config == dict(cfg):
                # Idempotent re-send: a wire-init retry whose previous
                # attempt lost only the REPLY (e.g. a commit acked
                # worker-side, torn on the way back) re-runs the whole
                # sequence — an identical config is a no-op, never a
                # spurious replica death out of the one retried lane.
                return {}
            raise ValueError(
                "put_config after engine construction — the engine "
                "geometry is fixed for a worker's lifetime (weights "
                "roll via push_*, geometry changes respawn)")
        self._pending_config = dict(cfg)
        self._maybe_init_ready()
        return {}

    def _rpc_push_begin(self, p: Dict) -> Dict:
        man = p.get("manifest")
        superseding = (
            isinstance(man, dict)
            and (man.get("version"), man.get("sha256"))
            != (self._params_version, self._params_sha))
        if self._committed_path is not None \
                and not self._engine_ready.is_set() and superseding:
            # A SUPERSEDING transfer (different version/digest) must
            # not land while the main thread is still building the
            # engine from the init artifact (it would prune the file
            # mid-load, or leave old weights under a new version
            # stamp) — wait the build out; the caller's RPC deadline
            # bounds us, exactly the first-step-after-spawn
            # discipline (size rpc_deadline above the engine build).
            # A re-push of the SAME artifact (a retry whose previous
            # attempt lost only the commit reply) proceeds
            # immediately: its bytes and commit are idempotent, so it
            # must never sit out the build burning the push budget.
            self._require_engine()
        asm = params_wire.ArtifactAssembler(self._ensure_artifact_dir())
        have = asm.begin(man)
        self._assembler = asm
        return {"have_bytes": have}

    def _rpc_push_chunk(self, p: Dict) -> Dict:
        if self._assembler is None:
            raise ValueError("push_chunk before push_begin")
        return {"have_bytes": self._assembler.write_chunk(p)}

    def _rpc_push_commit(self, p: Dict) -> Dict:
        asm = self._assembler
        if asm is None:
            raise ValueError("push_commit before push_begin")
        path, sha = asm.commit()
        version = int(asm.manifest["version"])
        self._assembler = None
        # One weight copy on disk, not one per roll: superseded
        # versions (full model artifacts) are pruned at commit.
        params_wire.prune_artifacts(self._ensure_artifact_dir(), path)
        if self._engine_ready.is_set():
            # Rolling update: the fleet drained this replica first, so
            # the engine is idle — swap weights in place, under the
            # lock, between steps. A busy engine raising here is the
            # drift signal, surfaced typed to the fleet.
            with open(path, "rb") as f:
                blob = f.read()
            params = params_wire.params_from_blob(blob, as_jax=True)
            with self._lock:
                self.engine.update_params(params)
        else:
            self._committed_path = path
        self._params_version, self._params_sha = version, sha
        self._maybe_init_ready()
        return {"version": version, "sha256": sha}

    def _maybe_init_ready(self) -> None:
        if self._pending_config is not None \
                and self._committed_path is not None:
            self._init_ready.set()

    # ------------------------------------------- engine RPCs

    def _rpc_submit(self, p: Dict) -> Dict:
        from horovod_tpu.serve.scheduler import make_request

        self._require_engine()
        with self._lock:
            eng = self.engine
            req = make_request(
                eng.config, eng.clock,
                np.asarray(p["prompt"], np.int32),
                int(p["max_new_tokens"]),
                temperature=float(p.get("temperature", 0.0)),
                top_k=int(p.get("top_k", 0)),
                eos_token=p.get("eos_token"),
                seed=int(p.get("seed", 0)),
                # reconstruct arrival in THIS process's clock so the
                # engine-side TTL sweep keeps the original deadline
                arrival=eng.clock() - float(p.get("age", 0.0)),
                ttl=p.get("ttl"))
            req._router_rid = int(p["rid"])
            # Disaggregated serving: a prefill-pool dispatch parks the
            # request in the engine's handoff bay at prefill
            # completion instead of decoding it here.
            req.prefill_only = bool(p.get("prefill_only", False))
            if eng.scheduler.submit(req):
                self._requests[int(p["rid"])] = req
                return {"accepted": True}
            # engine stamped the reject; report it inline (never also
            # via the outbox — the router owns the single record)
            if req in eng.scheduler.rejected:
                eng.scheduler.rejected.remove(req)
            return {"accepted": False,
                    "reject_reason": req.reject_reason,
                    "retry_after": req.retry_after}

    def _rpc_step(self, p: Dict) -> Dict:
        self._require_engine()
        with self._lock:
            eng = self.engine
            out = {"ticks": self._ticks,
                   "hb": self._hb_seq,
                   "free_slots": eng._free_slots(),
                   "occupancy": float(eng.cache.occupancy()),
                   "queue_len": len(eng.scheduler.queue),
                   "in_flight": eng.in_flight,
                   "idle": eng.idle,
                   # Disaggregated serving: router rids parked in the
                   # handoff bay, KV pages ready to ship. Readers must
                   # tolerate the key's absence (stub/pre-disagg
                   # workers never send it).
                   "handoff": [int(r._router_rid) for r in eng.handoff
                               if getattr(r, "_router_rid", None)
                               is not None]}
            # Prefix-cache snapshot (absent when caching is off — the
            # proxy, like every consumer, tolerates the missing key).
            ps = eng.prefix_stats() if hasattr(eng, "prefix_stats") \
                else None
            if ps is not None:
                out["prefix"] = {
                    "lookups": ps["lookups"], "hits": ps["hits"],
                    "tokens_hit": ps["tokens_hit"],
                    "entries": ps["entries"],
                    "pages_shared": ps["pages_shared"],
                }
            return out

    def _rpc_collect(self, p: Dict) -> Dict:
        since = p.get("since") or {}
        self._require_engine()
        with self._lock:
            self._harvest_locked()
            events, self._terminal = self._terminal, []
            progress = []
            for rid_s, n in since.items():
                req = self._requests.get(int(rid_s))
                if req is None:
                    continue   # terminal event already covers it
                progress.append({
                    "rid": int(rid_s),
                    "tokens": [int(t) for t in req.output[int(n):]],
                    "prefill_pos": int(req.prefill_pos),
                    "generated_len": len(req.generated),
                    # Live prefix stamps: the router mirror must see
                    # them BEFORE a crash-drain reads its baseline.
                    "prefix_hit_tokens": int(getattr(
                        req, "prefix_hit_tokens", 0)),
                    "prefix_hit_pages": int(getattr(
                        req, "prefix_hit_pages", 0)),
                })
        self._collects += 1
        return {"events": events, "progress": progress,
                "hb": self._hb_seq}

    def _rpc_stats(self, p: Dict) -> Dict:
        self._require_engine()
        with self._lock:
            return _jsonable(self.engine.stats())

    def _rpc_drain(self, p: Dict) -> Dict:
        self._require_engine()
        deadline = time.monotonic() + float(p.get("timeout", 5.0))
        while time.monotonic() < deadline:
            with self._lock:
                if self.engine.idle:
                    return {"idle": True}
            time.sleep(0.005)
        return {"idle": False}

    def _rpc_reset_metrics(self, p: Dict) -> Dict:
        self._require_engine()
        with self._lock:
            self.engine.reset_metrics()   # raises if not idle
            self._ticks = 0
        return {"ticks": 0}

    def _rpc_fault(self, p: Dict) -> Dict:
        # Deliberately NO _require_engine: fault arming only sets host
        # flags the serve loop consumes post-attach, and the fleet may
        # arm a fault in the same tick that wire-inits this worker —
        # waiting here would deadlock against the very thread whose
        # pushes make the engine ready.
        kind = p.get("kind")
        with self._lock:
            if kind == "stall":
                self._stall_pending = {"secs": p.get("secs")}
            elif kind == "slow":
                self._slow = float(p["factor"])
            else:
                raise ValueError(f"unknown fault kind {kind!r} (the "
                                 "kill edition is a real signal)")
        return {}

    # ------------------------------------- disaggregated KV transfer
    #
    # kv_export_* (prefill side) / kv_import_* (decode side): the KV
    # handoff lane (serve/kv_wire.py over serve/chunk_stream.py). The
    # SAME framing/CRC/resume discipline as the params push — but NOT
    # a retried lane: a TransportError mid-transfer takes the death
    # path (drain -> rebase_for_recompute -> requeue, at-most-once);
    # only a still-healthy pair resumes (begin returns have_bytes).

    def _rpc_kv_export_begin(self, p: Dict) -> Dict:
        from horovod_tpu.serve.kv_wire import KvSender

        eng = self._require_engine()
        rid = int(p["rid"])
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                raise ValueError(
                    f"kv_export_begin: rid {rid} is not live here "
                    "(expired, finished, or never dispatched)")
            # KeyError (typed over the wire) when not parked: the
            # request expired or finished before the fleet asked.
            blob = eng.export_handoff(req.rid)
        cb = int(p.get("chunk_bytes")
                 or params_wire.DEFAULT_CHUNK_BYTES)
        sender = KvSender(blob, rid, cb)
        self._kv_senders[rid] = sender
        return {"manifest": sender.manifest}

    def _rpc_kv_export_chunk(self, p: Dict) -> Dict:
        rid = int(p["rid"])
        sender = self._kv_senders.get(rid)
        if sender is None:
            raise ValueError(f"kv_export_chunk: no open export for "
                             f"rid {rid}")
        return {"chunk": sender.chunk(int(p["index"]))}

    def _rpc_kv_export_end(self, p: Dict) -> Dict:
        """Close one export. ``commit=True`` (the decode side ACKED its
        digest-verified import): release the parked request's pages and
        forget the rid WITHOUT a terminal event — ownership moved, the
        stream did not end. ``commit=False``: drop only the sender; the
        request stays parked for a retry or redispatch."""
        rid = int(p["rid"])
        self._kv_senders.pop(rid, None)
        if not p.get("commit", True):
            return {}
        self._require_engine()
        with self._lock:
            req = self._requests.pop(rid, None)
            if req is not None:
                self.engine.release_handoff(req.rid)
        return {}

    def _rpc_kv_import_begin(self, p: Dict) -> Dict:
        from horovod_tpu.serve.kv_wire import KvReceiver
        from horovod_tpu.serve.scheduler import make_request

        eng = self._require_engine()
        rid = int(p["rid"])
        r = p["req"]
        with self._lock:
            req = make_request(
                eng.config, eng.clock,
                np.asarray(r["prompt"], np.int32),
                int(r["max_new_tokens"]),
                temperature=float(r.get("temperature", 0.0)),
                top_k=int(r.get("top_k", 0)),
                eos_token=r.get("eos_token"),
                seed=int(r.get("seed", 0)),
                arrival=eng.clock() - float(r.get("age", 0.0)),
                ttl=r.get("ttl"))
            req._router_rid = rid
            # The prefill side already emitted these (normally just the
            # first token): they count against the budget and position
            # the sampler, and collect(since=N) never re-streams them.
            req.generated = [int(t) for t in r.get("generated", [])]
            req.output = list(req.generated)
        # A re-begin for the same rid reuses the receiver — the
        # assembled prefix survives for resume-from-offset.
        recv = self._kv_receivers.get(rid)
        if recv is None:
            recv = KvReceiver(rid)
            self._kv_receivers[rid] = recv
        recv.req = req
        return {"have_bytes": recv.begin(p["manifest"])}

    def _rpc_kv_import_chunk(self, p: Dict) -> Dict:
        rid = int(p["rid"])
        recv = self._kv_receivers.get(rid)
        if recv is None:
            raise ValueError(f"kv_import_chunk: no open import for "
                             f"rid {rid}")
        return {"have_bytes": recv.write_chunk(p["chunk"])}

    def _rpc_kv_import_commit(self, p: Dict) -> Dict:
        """Digest-verify the assembled blob and admit the request into
        THIS engine at its handoff position. The receiver is dropped
        only on SUCCESS — a failed admit (pages filled up since the
        router's check) keeps the assembled bytes, so a later retry
        re-commits without re-shipping."""
        rid = int(p["rid"])
        recv = self._kv_receivers.get(rid)
        if recv is None:
            raise ValueError(f"kv_import_commit: no open import for "
                             f"rid {rid}")
        blob = recv.commit()
        self._require_engine()
        with self._lock:
            self.engine.admit_prefilled(recv.req, blob)
            self._requests[rid] = recv.req
        del self._kv_receivers[rid]
        return {"accepted": True}

    def _rpc_kv_import_abort(self, p: Dict) -> Dict:
        recv = self._kv_receivers.pop(int(p["rid"]), None)
        if recv is not None:
            recv.abort()
        return {}

    def _rpc_shutdown(self, p: Dict) -> Dict:
        self._shutdown.set()
        # The engine thread may be genuinely wedged (a bounded stall
        # mid-sleep): guarantee exit shortly after the reply flushes,
        # through the taxonomy's clean code either way.
        timer = threading.Timer(0.5, os._exit, args=(EXIT_CLEAN,))
        timer.daemon = True
        timer.start()
        return {"pid": os.getpid()}

    # ---------------------------------------------- plumbing

    def _send_hook(self, sock: socket.socket, frame: bytes) -> bool:
        if self._torn_after is not None \
                and self._collects >= self._torn_after:
            sock.settimeout(5.0)
            sock.sendall(frame[:max(1, len(frame) // 2)])
            os._exit(1)   # die mid-write: the torn-frame crash shape
        return False

    def rpc_loop(self, server_sock: socket.socket) -> None:
        from horovod_tpu.serve.transport import server_handshake

        while not self._shutdown.is_set():
            server_sock.settimeout(0.25)
            try:
                conn, _ = server_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                if self._secret:
                    # TCP listener: anything that routes to the port
                    # can connect — prove the fleet secret before a
                    # single RPC frame is served, drop otherwise.
                    if not server_handshake(
                            conn, self._secret,
                            time.monotonic() + 5.0):
                        continue
                serve_connection(conn, self.handle,
                                 should_stop=self._shutdown.is_set,
                                 send_hook=self._send_hook)


# ------------------------------------------------------------------- main


def main(argv=None) -> int:
    # Startup-failure test hook: before ANY heavy work, so the fleet
    # sees a worker that dies pre-bind, pre-heartbeat (classified
    # crashed, consumes restart budget — docs/troubleshooting.md).
    fail = os.environ.get("HVD_SERVE_WORKER_FAIL_START")
    if fail:
        print("serve.worker: HVD_SERVE_WORKER_FAIL_START set — "
              "exiting before startup", file=sys.stderr, flush=True)
        return int(fail)

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve.worker",
        description="One serving-fleet replica worker process.")
    ap.add_argument("--socket", default="",
                    help="Unix-domain socket path to serve RPCs on "
                         "(the same-host 'process' transport)")
    ap.add_argument("--bind", default="",
                    help="TCP 'host:port' to listen on instead of a "
                         "unix socket (the multi-host 'tcp' "
                         "transport; port 0 = ephemeral). Requires "
                         "HOROVOD_SECRET in the environment — a TCP "
                         "listener is network-reachable, so every "
                         "connection must pass the shared-secret "
                         "handshake")
    ap.add_argument("--params", default="",
                    help="params artifact file (worker.save_params). "
                         "Omit BOTH --params and --config for wire "
                         "init: config + params then arrive over the "
                         "RPC wire (put_config + push_*) — the fleet's "
                         "default, no filesystem assumption")
    ap.add_argument("--config", default="",
                    help="path to the ServeConfig JSON (file mode; "
                         "see --params)")
    ap.add_argument("--params-version", type=int, default=1,
                    help="artifact version stamp for file mode (wire "
                         "init takes it from the pushed manifest)")
    ap.add_argument("--rank", type=int, default=0,
                    help="replica id (heartbeat file + logs)")
    ap.add_argument("--heartbeat-dir", default="",
                    help="fleet heartbeat directory ('' = no beacon; "
                         "tcp workers normally run without one — "
                         "liveness rides the transport)")
    args = ap.parse_args(argv)
    if bool(args.socket) == bool(args.bind):
        ap.error("exactly one of --socket (unix) or --bind host:port "
                 "(tcp) is required")
    if bool(args.params) != bool(args.config):
        ap.error("--params and --config come together (file mode) or "
                 "not at all (wire init: both arrive over the RPC "
                 "wire)")

    # Bind BEFORE the heavy init: the router's connect succeeds as soon
    # as the process is alive; its first RPCs wait inside their own
    # deadline for the engine to finish constructing.
    secret = ""
    if args.bind:
        host, _, port_s = args.bind.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            print(f"serve.worker[{args.rank}]: --bind {args.bind!r} is "
                  "not host:port", file=sys.stderr, flush=True)
            return EXIT_USAGE
        secret = os.environ.get("HOROVOD_SECRET", "")
        if not secret:
            print(f"serve.worker[{args.rank}]: --bind needs "
                  "HOROVOD_SECRET in the environment — refusing to "
                  "serve an unauthenticated network listener",
                  file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host or "0.0.0.0", port))
        except OSError as e:
            print(f"serve.worker[{args.rank}]: cannot bind "
                  f"{args.bind}: {e}", file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv.listen(2)
        bound_port = srv.getsockname()[1]
        # Advertised-address resolution (run/network.py's offline-safe
        # fallback chain): which endpoint peers should dial when the
        # bind address is a wildcard.
        from horovod_tpu.run.network import advertise_ip

        adv = host if host and host != "0.0.0.0" else advertise_ip()
        print(f"serve.worker[{args.rank}]: tcp listener on "
              f"{args.bind} (advertise {adv}:{bound_port})",
              file=sys.stderr, flush=True)
    else:
        try:
            os.unlink(args.socket)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(args.socket)
        except OSError as e:
            print(f"serve.worker[{args.rank}]: cannot bind "
                  f"{args.socket}: {e}", file=sys.stderr, flush=True)
            return EXIT_USAGE
        srv.listen(2)

    def _build_engine(cfg_kwargs, params_path):
        # The heavy half, shared by both modes: jax import + engine
        # construction. Runs AFTER the socket is bound, so the
        # router's connect always succeeds early.
        import jax

        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            # This image's sitecustomize imports jax at interpreter
            # startup (the conftest note): config.update is the
            # reliable override.
            jax.config.update("jax_platforms", plat.split(",")[0])

        from horovod_tpu.serve.config import ServeConfig
        from horovod_tpu.serve.engine import ServeEngine

        cfg = ServeConfig(**cfg_kwargs)
        return ServeEngine(load_params(params_path), cfg)

    from horovod_tpu.elastic.signals import Heartbeat

    hb = Heartbeat(args.heartbeat_dir, rank=args.rank) \
        if args.heartbeat_dir else None

    if not args.params:
        # WIRE INIT: serve the transfer RPCs first (pure file I/O, no
        # jax), build the engine only once a digest-verified artifact
        # and the config have both arrived over the wire.
        host_loop = WorkerHost(None, None, secret=secret or None)
        rpc = threading.Thread(target=host_loop.rpc_loop, args=(srv,),
                               daemon=True,
                               name=f"serve-worker-rpc-{args.rank}")
        rpc.start()
        print(f"serve.worker[{args.rank}]: serving on "
              f"{args.bind or args.socket} (pid {os.getpid()}) — "
              "awaiting config + params over the wire",
              file=sys.stderr, flush=True)
        init_timeout = float(os.environ.get(
            "HVD_SERVE_WORKER_INIT_TIMEOUT", "600"))
        if not host_loop.wait_init(init_timeout):
            print(f"serve.worker[{args.rank}]: no config/params "
                  f"arrived within {init_timeout:g}s — exiting",
                  file=sys.stderr, flush=True)
            srv.close()
            return EXIT_USAGE
        engine = _build_engine(host_loop.init_config,
                               host_loop.init_params_path)
        host_loop.attach_engine(engine, hb)
        print(f"serve.worker[{args.rank}]: engine up on params "
              f"v{host_loop._params_version} "
              f"(sha256 {(host_loop._params_sha or '')[:12]})",
              file=sys.stderr, flush=True)
    else:
        # FILE MODE (standalone / debugging): params + config from
        # disk, version stamped from the CLI, sha from the file bytes.
        with open(args.config) as f:
            cfg_kwargs = json.load(f)
        engine = _build_engine(cfg_kwargs, args.params)
        with open(args.params, "rb") as f:
            sha = params_wire.sha256_hex(f.read())
        host_loop = WorkerHost(engine, hb, secret=secret or None,
                               params_version=args.params_version,
                               params_sha=sha)
        rpc = threading.Thread(target=host_loop.rpc_loop, args=(srv,),
                               daemon=True,
                               name=f"serve-worker-rpc-{args.rank}")
        rpc.start()
        print(f"serve.worker[{args.rank}]: serving on "
              f"{args.bind or args.socket} (pid {os.getpid()})",
              file=sys.stderr, flush=True)
    host_loop.serve_loop()
    srv.close()
    return EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
