"""Deadline-checked frame protocol for the cross-process serving fleet.

One replica worker (:mod:`horovod_tpu.serve.worker`) serves its RPCs
over a Unix-domain socket (``FleetConfig(transport="process")``) or a
TCP listener (``transport="tcp"`` — the multi-host placement); the
router side (:class:`~horovod_tpu.serve.fleet.ServeFleet`) talks to it
through :class:`RpcClient` either way — the address is a filesystem
path for Unix sockets or a ``(host, port)`` tuple for TCP, and the
frame discipline below is byte-identical on both. The wire format is
deliberately minimal and fully checkable:

``[4B magic "HVSF"][4B big-endian payload length][4B CRC32][payload]``

with the payload UTF-8 JSON. No pickle: the peer is a child process of
the router, but a worker that died mid-write (the whole point of this
transport is surviving exactly that) leaves arbitrary byte garbage on
the stream, and a codec that cannot mis-parse garbage into a live
object is the difference between "replica crashed, drained, and
redispatched" and a corrupted router.

Failure taxonomy — every way the wire can fail maps to ONE typed
exception, and every receive is bounded by a deadline (the silent-hang
shape this module must never have is lint rule HVD011):

* :class:`DeadlineExceeded` — the per-RPC deadline expired (worker
  wedged mid-compute, or a frame stopped arriving mid-stream);
* :class:`ConnectionLost` — refused / reset / EOF *between* frames
  (the worker process is gone);
* :class:`FrameError` — a torn frame (EOF or garbage mid-frame: the
  kill-mid-write shape), bad magic, an oversized length, undecodable
  payload, or a duplicated/interleaved reply (response id mismatch);
* :class:`ChecksumError` — the frame arrived complete but its CRC32
  does not match (bit corruption);
* :class:`RemoteCallError` — the frame layer is healthy but the worker
  raised inside the handler.

The RPC layer never retries: any :class:`TransportError` means the
caller must treat the replica as DEAD and route into the fleet's
drain/redispatch path (at-most-once delivery is the fleet's invariant,
and a blind resend could double-apply a ``submit``). docs/serving.md
"Process fleet" / "Multi-host fleet" carry the deadline table and the
failure → action matrix.

TCP adds one thing Unix sockets never needed: a **connect handshake**.
A Unix socket is reachable only through the filesystem; a TCP listener
is reachable by anything that can route to the port, so every accepted
connection must prove it holds the fleet's shared secret before a
single RPC frame is served (:func:`server_handshake` /
:func:`client_handshake` — an HMAC-SHA256 challenge/response over the
same frame codec, the ``run/network.py`` secret discipline applied to
the serving wire; the secret itself never crosses the wire, and over
ssh placement it ships via stdin, never argv).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import re
import socket
import struct
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

#: An RPC endpoint: a Unix-socket filesystem path, or a TCP
#: ``(host, port)`` pair.
Address = Union[str, Tuple[str, int]]

#: Frame magic. A reply that starts with anything else is byte garbage
#: (a torn previous frame, or a non-worker peer) — never parsed.
MAGIC = b"HVSF"
_HEADER = struct.Struct(">4sII")   # magic, payload length, CRC32
HEADER_LEN = _HEADER.size

#: Frames are control-plane JSON (requests, token ids, stats) — a
#: length field above this is corruption, not a real payload, and must
#: not turn into a giant allocation + an unbounded read.
MAX_FRAME = 16 << 20

#: recv() slice while waiting out a deadline, so a close()d socket or
#: process exit is noticed promptly even under a long deadline.
_POLL_SLICE = 0.25


class TransportError(RuntimeError):
    """Base of every wire failure. The fleet maps ANY of these to the
    replica-death path (drain + redispatch + relaunch) — no RPC-level
    retry, ever."""


class DeadlineExceeded(TransportError):
    """The per-RPC deadline expired before the full reply arrived."""


class ConnectionLost(TransportError):
    """Connection refused/reset, or EOF on a frame boundary — the
    worker process is gone (or never came up)."""


class FrameError(TransportError):
    """Torn or malformed frame: EOF mid-frame (kill-mid-write), bad
    magic, oversized length, undecodable payload, or a reply whose id
    does not match the in-flight request (duplicate/interleave)."""


class ChecksumError(FrameError):
    """Complete frame, wrong CRC32: the bytes were corrupted in
    flight or by a partially-flushed writer."""


class RemoteCallError(TransportError):
    """The worker's handler raised; the error text rode back over a
    healthy frame layer. Still a replica-death signal: an engine that
    raises mid-step is the crash shape (the in-process fleet treats it
    identically). The ONE exception is the params-push lane
    (``push_begin``/``push_chunk``/``push_commit``): chunk writes are
    idempotent and digest-verified, so the fleet retries those under
    its budgeted backoff instead of killing the replica — see
    :func:`remote_error_kind` for how a worker-side typed rejection
    (e.g. the transfer codec's ``ChecksumError``) is classified."""


def remote_error_kind(err: TransportError) -> str:
    """Incident-classification label for a transport failure: for a
    :class:`RemoteCallError` the WORKER-side exception class name (the
    handler's typed error — e.g. the transfer codec's ``ChecksumError``
    riding back over a healthy frame layer), else the local typed
    class. The fleet stamps this into ``transfer_incidents`` /
    ``transport_incidents`` so a corrupted chunk and a torn connection
    stay distinguishable in the record. The class name rides the
    reply's structured ``error_type`` field (set by
    :func:`serve_connection`, stamped onto the exception by
    :meth:`RpcClient.call`); the message-parse below is only the
    fallback for a peer speaking an older reply shape."""
    if isinstance(err, RemoteCallError):
        kind = getattr(err, "remote_type", None)
        if kind:
            return str(kind)
        m = re.search(r"worker raised: ([A-Za-z_][A-Za-z0-9_]*)",
                      str(err))
        if m:
            return m.group(1)
    return type(err).__name__


def encode_frame(obj: Any) -> bytes:
    """One message -> wire bytes (header + JSON payload)."""
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise FrameError(
            f"payload of {len(payload)} bytes exceeds MAX_FRAME "
            f"({MAX_FRAME}) — not a control-plane message")
    return _HEADER.pack(MAGIC, len(payload),
                        zlib.crc32(payload)) + payload


def _deadline(timeout: Optional[float]) -> Optional[float]:
    return None if timeout is None else time.monotonic() + timeout


def _remaining(deadline: Optional[float]) -> Optional[float]:
    if deadline is None:
        return None
    return deadline - time.monotonic()


def recv_exact(sock: socket.socket, n: int, deadline: Optional[float],
               *, mid_frame: bool) -> bytes:
    """Read exactly ``n`` bytes with every recv bounded by ``deadline``
    (an absolute ``time.monotonic`` stamp; None = wait forever, which
    no fleet-side caller uses). EOF maps to :class:`ConnectionLost` on
    a frame boundary (``mid_frame=False``, nothing read yet) and to
    :class:`FrameError` once any frame byte has been consumed — the
    kill-mid-write distinction the drain path keys on."""
    buf = b""
    while len(buf) < n:
        remaining = _remaining(deadline)
        if remaining is not None and remaining <= 0:
            raise DeadlineExceeded(
                f"deadline expired after {len(buf)}/{n} bytes")
        slice_ = _POLL_SLICE if remaining is None \
            else min(_POLL_SLICE, remaining)
        sock.settimeout(slice_)
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue   # poll slice over; re-check the real deadline
        except (ConnectionResetError, BrokenPipeError) as e:
            raise ConnectionLost(f"connection reset: {e}") from None
        except OSError as e:
            raise ConnectionLost(f"socket error: {e}") from None
        if not chunk:
            if mid_frame or buf:
                raise FrameError(
                    f"torn frame: peer closed after {len(buf)}/{n} "
                    "bytes (writer died mid-frame)")
            raise ConnectionLost("peer closed the connection")
        buf += chunk
    return buf


def send_frame(sock: socket.socket, obj: Any,
               deadline: Optional[float]) -> None:
    """Write one frame, bounded by ``deadline`` (absolute monotonic)."""
    data = encode_frame(obj)
    remaining = _remaining(deadline)
    if remaining is not None and remaining <= 0:
        raise DeadlineExceeded("deadline expired before send")
    sock.settimeout(remaining)
    try:
        sock.sendall(data)
    except socket.timeout:
        raise DeadlineExceeded(
            "deadline expired mid-send (peer not draining)") from None
    except (ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionLost(f"connection lost mid-send: {e}") from None
    except OSError as e:
        raise ConnectionLost(f"socket error mid-send: {e}") from None


def recv_frame(sock: socket.socket, deadline: Optional[float]) -> Any:
    """Read + validate one frame; returns the decoded JSON value.
    Every corruption mode raises a typed :class:`TransportError` —
    never a hang (deadline-bounded reads), never a mis-parsed payload
    (magic + length bound + CRC32 + strict JSON)."""
    header = recv_exact(sock, HEADER_LEN, deadline, mid_frame=False)
    magic, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(
            f"bad frame magic {magic!r} (desynchronized or corrupt "
            "stream)")
    if length > MAX_FRAME:
        raise FrameError(
            f"frame length {length} exceeds MAX_FRAME ({MAX_FRAME}) — "
            "corrupt length field")
    payload = recv_exact(sock, length, deadline, mid_frame=True)
    if zlib.crc32(payload) != crc:
        raise ChecksumError(
            f"checksum mismatch on a {length}-byte frame")
    try:
        return json.loads(payload.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"undecodable frame payload: {e}") from None


# ------------------------------------------------------------- handshake
#
# TCP listeners are network-reachable, so a connection must prove it
# holds the fleet's shared secret before any RPC is served. The
# challenge/response rides the frame codec itself: server sends a
# random nonce, client answers HMAC-SHA256(secret, nonce), server
# compares in constant time and acks. An unauthenticated peer never
# reaches the handler, and the secret never crosses the wire.


def _handshake_mac(secret: str, nonce: str) -> str:
    # utf-8 on both legs: encoding a str can then never raise, so an
    # adversarial (non-ASCII) nonce or auth value from the wire can
    # only ever FAIL the comparison — never throw past the typed
    # taxonomy (a TypeError/UnicodeEncodeError here would kill the
    # worker's only accept thread / leak the client's socket).
    return hmac.new(secret.encode("utf-8"), nonce.encode("utf-8"),
                    hashlib.sha256).hexdigest()


def server_handshake(sock: socket.socket, secret: str,
                     deadline: Optional[float]) -> bool:
    """Worker-side challenge/response on one accepted connection.
    Returns True when the peer proved the shared secret; False (after a
    best-effort rejection ack) otherwise — the caller drops the
    connection and keeps accepting. Never raises: a garbage or silent
    peer is just an unauthenticated one."""
    nonce = os.urandom(16).hex()
    try:
        send_frame(sock, {"hvsf": 1, "nonce": nonce}, deadline)
        reply = recv_frame(sock, deadline)
    except TransportError:
        return False
    auth = reply.get("auth") if isinstance(reply, dict) else None
    # Compare BYTES: compare_digest on str raises TypeError for
    # non-ASCII input, and this function's contract is never-raise —
    # an unauthenticated peer must only ever be dropped.
    ok = isinstance(auth, str) and hmac.compare_digest(
        auth.encode("utf-8"),
        _handshake_mac(secret, nonce).encode("utf-8"))
    try:
        send_frame(sock, {"ok": bool(ok)}, deadline)
    except TransportError:
        return False
    return ok


def client_handshake(sock: socket.socket, secret: str,
                     deadline: Optional[float]) -> None:
    """Router-side half: answer the server's nonce challenge. Raises a
    typed :class:`TransportError` on any failure — a rejected handshake
    (secret mismatch) is :class:`ConnectionLost`, because to the fleet
    it IS one: the replica can never be spoken to."""
    challenge = recv_frame(sock, deadline)
    nonce = challenge.get("nonce") if isinstance(challenge, dict) else None
    if not isinstance(nonce, str):
        raise FrameError(
            f"handshake: expected a nonce challenge, got {challenge!r}")
    send_frame(sock, {"auth": _handshake_mac(secret, nonce)}, deadline)
    ack = recv_frame(sock, deadline)
    if not (isinstance(ack, dict) and ack.get("ok")):
        raise ConnectionLost(
            "handshake rejected by the worker — shared-secret mismatch "
            "(is HOROVOD_SECRET the fleet's secret on both ends?)")


class RpcClient:
    """Fleet-side RPC stub over one Unix-socket or TCP connection.

    Every :meth:`call` carries its own deadline (``timeout``, default
    ``default_timeout``); the request/response pair shares it — a
    worker that accepted the request but never answers is
    indistinguishable from one that wedged mid-parse, and both resolve
    as :class:`DeadlineExceeded` within the budget. Replies carry the
    request's ``id`` and a mismatch (a duplicated or interleaved frame,
    e.g. a stale reply surviving a half-torn stream) raises
    :class:`FrameError`. After ANY transport error the connection is
    closed; on the normal RPC surface the fleet then replaces the
    replica — it never resends (a resent ``submit`` could
    double-apply). The ONE exception is the params-push lane
    (``push_begin``/``push_chunk``/``push_commit``): those calls are
    idempotent and digest-verified, so the fleet retries them through
    this same client (the next :meth:`call` reconnects), resuming the
    transfer from the worker's verified offset.

    ``proc_alive`` (optional callable) lets :meth:`connect` fail fast
    with :class:`ConnectionLost` when the worker process has already
    exited instead of retrying the socket until the deadline — the
    worker-dies-on-startup shape.

    ``connect_timeout`` (optional) separately bounds how long the
    FIRST connect after a (re)spawn may retry while the worker binds
    its socket — the fleet passes ``FleetConfig.spawn_timeout`` so a
    worker that never comes up fails at
    ``min(spawn_timeout, rpc_deadline)`` rather than consuming a
    generous per-RPC budget on every doomed call.

    ``call_ms`` (optional shared list) accumulates per-call wall
    milliseconds — the fleet aggregates them across replica
    incarnations into the ``rpc_ms`` overhead stamp.

    ``path`` may be a Unix-socket filesystem path or a TCP
    ``(host, port)`` tuple. TCP connections additionally take
    ``secret`` (the fleet's shared secret: every fresh connection runs
    the :func:`client_handshake` challenge/response before the first
    RPC) and ``sock_wrap`` (a ``sock -> sock`` hook applied to every
    fresh connection — the seam the deterministic network fault
    injector, :mod:`horovod_tpu.serve.netfault`, plugs into).
    """

    def __init__(self, path: Address, *, default_timeout: float = 60.0,
                 connect_timeout: Optional[float] = None,
                 proc_alive: Optional[Callable[[], bool]] = None,
                 call_ms: Optional[List[float]] = None,
                 secret: Optional[str] = None,
                 sock_wrap: Optional[
                     Callable[[socket.socket], socket.socket]] = None):
        self.path = path
        self.default_timeout = float(default_timeout)
        self.connect_timeout = connect_timeout
        self._proc_alive = proc_alive
        self.call_ms = call_ms if call_ms is not None else []
        self.secret = secret
        self._sock_wrap = sock_wrap
        self._sock: Optional[socket.socket] = None
        self._next_id = 0

    @property
    def connected(self) -> bool:
        return self._sock is not None

    @property
    def _is_tcp(self) -> bool:
        return isinstance(self.path, tuple)

    def _endpoint(self) -> str:
        return (f"{self.path[0]}:{self.path[1]}" if self._is_tcp
                else str(self.path))

    def connect(self, timeout: Optional[float] = None) -> None:
        """Connect, retrying while the socket file is absent or the
        listener not yet up (the worker binds before its heavy jax
        init, but a relaunch can race). Gives up early when
        ``proc_alive`` reports the worker dead. TCP connections run the
        shared-secret handshake before the client counts as connected —
        a replica we cannot authenticate to is one we cannot speak to."""
        if self._sock is not None:
            return
        deadline = _deadline(timeout if timeout is not None
                             else self.default_timeout)
        family = socket.AF_INET if self._is_tcp else socket.AF_UNIX
        target = tuple(self.path) if self._is_tcp else self.path
        while True:
            sock = socket.socket(family, socket.SOCK_STREAM)
            try:
                remaining = _remaining(deadline)
                if remaining is not None and remaining <= 0:
                    sock.close()
                    raise DeadlineExceeded(
                        f"could not connect to worker at "
                        f"{self._endpoint()} before the deadline")
                sock.settimeout(remaining)
                sock.connect(target)
                break
            except socket.timeout:
                sock.close()
                raise DeadlineExceeded(
                    f"connect to {self._endpoint()} timed out") from None
            except (FileNotFoundError, ConnectionRefusedError) as e:
                sock.close()
                if self._proc_alive is not None and \
                        not self._proc_alive():
                    raise ConnectionLost(
                        f"worker exited before serving "
                        f"{self._endpoint()} (died on startup?)"
                    ) from None
                remaining = _remaining(deadline)
                if remaining is not None and remaining <= 0:
                    raise DeadlineExceeded(
                        f"worker never listened on {self._endpoint()}: "
                        f"{e}") from None
                time.sleep(0.02)
            except OSError as e:
                sock.close()
                raise ConnectionLost(
                    f"connect to {self._endpoint()} failed: {e}"
                ) from None
        if self._sock_wrap is not None:
            sock = self._sock_wrap(sock)
        if self.secret is not None:
            try:
                client_handshake(sock, self.secret, deadline)
            except Exception:
                # Typed or not (defense in depth), a failed handshake
                # must never leak the connected socket.
                sock.close()
                raise
        self._sock = sock

    def call(self, method: str, params: Optional[Dict] = None,
             timeout: Optional[float] = None) -> Any:
        """One request/response round trip under one deadline."""
        budget = self.default_timeout if timeout is None else timeout
        deadline = _deadline(budget)
        if self._sock is None:
            connect_budget = _remaining(deadline)
            if self.connect_timeout is not None:
                connect_budget = min(connect_budget,
                                     self.connect_timeout)
            self.connect(connect_budget)
        rid = self._next_id
        self._next_id += 1
        t0 = time.perf_counter()
        try:
            send_frame(self._sock, {"id": rid, "method": method,
                                    "params": params or {}}, deadline)
            resp = recv_frame(self._sock, deadline)
        except TransportError:
            self.close()
            raise
        self.call_ms.append((time.perf_counter() - t0) * 1e3)
        if not isinstance(resp, dict) or resp.get("id") != rid:
            self.close()
            raise FrameError(
                f"reply id {resp.get('id') if isinstance(resp, dict) else resp!r} "
                f"does not match request id {rid} (duplicated or "
                "interleaved frame)")
        if not resp.get("ok"):
            err = RemoteCallError(
                f"{method}: worker raised: {resp.get('error')}")
            # Structured worker-side exception class (what
            # remote_error_kind classifies by) — never parsed back out
            # of the human-readable message.
            err.remote_type = resp.get("error_type")
            raise err
        return resp.get("result")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def serve_connection(sock: socket.socket,
                     handler: Callable[[str, Dict], Any],
                     *, idle_timeout: Optional[float] = None,
                     should_stop: Optional[Callable[[], bool]] = None,
                     send_hook: Optional[
                         Callable[[socket.socket, bytes], bool]] = None
                     ) -> None:
    """Worker-side request loop over ONE accepted connection.

    Each request is answered with ``{"id", "ok", "result"}`` or
    ``{"id", "ok": False, "error"}`` (handler exceptions ride back as
    errors — the client surfaces them as :class:`RemoteCallError`).
    Waiting for the NEXT request polls in deadline-bounded slices
    (never an unbounded recv — rule HVD011 applies to the worker too)
    so ``should_stop`` is honored promptly; ``idle_timeout`` bounds
    how long an idle connection is held. Returns when the peer
    disconnects, the idle timeout passes, or ``should_stop`` fires.

    ``send_hook(sock, frame_bytes) -> bool`` (test instrumentation)
    may take over sending a reply; returning True means it did.
    """
    idle_since = time.monotonic()
    while True:
        if should_stop is not None and should_stop():
            return
        # Idle wait is a PEEK in poll slices, separate from the frame
        # read: a frame arriving slowly across slices must not have its
        # first bytes consumed-and-discarded by an aborted read (that
        # would desynchronize the stream on the next loop).
        sock.settimeout(_POLL_SLICE)
        try:
            first = sock.recv(1, socket.MSG_PEEK)
        except socket.timeout:
            if idle_timeout is not None and \
                    time.monotonic() - idle_since > idle_timeout:
                return
            continue
        except OSError:
            return
        if not first:
            return   # peer closed between frames
        try:
            req = recv_frame(sock, _deadline(30.0))
        except TransportError:
            return     # peer gone or stream corrupt: drop the conn
        idle_since = time.monotonic()
        rid = req.get("id") if isinstance(req, dict) else None
        try:
            result = handler(req.get("method", ""),
                             req.get("params") or {})
            resp = {"id": rid, "ok": True, "result": result}
        except Exception as e:   # surfaced to the client, conn lives
            resp = {"id": rid, "ok": False,
                    "error_type": type(e).__name__,
                    "error": f"{type(e).__name__}: {e}"}
        frame = encode_frame(resp)
        if send_hook is not None and send_hook(sock, frame):
            continue
        try:
            sock.settimeout(30.0)
            sock.sendall(frame)
        except OSError:
            return


__all__ = [
    "Address", "ChecksumError", "ConnectionLost", "DeadlineExceeded",
    "FrameError", "HEADER_LEN", "MAGIC", "MAX_FRAME", "RemoteCallError",
    "RpcClient", "TransportError", "client_handshake", "encode_frame",
    "recv_exact", "recv_frame", "remote_error_kind", "send_frame",
    "serve_connection", "server_handshake",
]
