"""Block/paged KV cache: fixed-size pages over the static cache layout.

vLLM's PagedAttention idea, restated for the TPU compilation model: the
compiled step program only ever sees FIXED-shape page arrays
(``[num_pages, page_size, H, D]`` per layer per K/V) plus per-request
page-table index vectors — so paging is pure data (gather/scatter
indices), never a reshape, and the program compiles once. A request's
logical cache positions ``0..Lmax-1`` map through its page table to
physical pages; the gather of a full table reconstructs exactly the
``[Lmax, H, D]`` contiguous cache :func:`models.parallel_lm.lm_decode`
uses, which is what keeps the engine token-exact with the decode lane.

Host side, this module is bookkeeping only (the hot path is inside the
engine's compiled program): a free-list :class:`PageAllocator` with
all-or-nothing grants, and :class:`PagedKVCache` tying the allocator to
the device arrays + the admission-control page math. Fixed-size pages
never fragment externally — exhaustion, not fragmentation, is the
failure mode, and admission control (reserve worst case up front) or
eviction (lazy mode) handles it; tests/test_serve_kvcache.py property-
tests the invariants.

Pages are REFCOUNTED so prefix caching (``serve/prefix.py``, SGLang's
RadixAttention idea) can map one filled page read-only into many
requests' page tables: :meth:`PageAllocator.retain` adds a holder,
:meth:`PageAllocator.release` drops one and returns the page to the
free list only at refcount zero — a shared page can never re-enter the
free list while any holder remains. Everything outside this module
releases through the refcounted path; a direct :meth:`PageAllocator.
free` elsewhere is lint rule HVD013 (it would double-free under
sharing). Writes to a shared page copy-on-write first
(:meth:`PagedKVCache.cow_page`) — a page copy + table swap, cheap
because the engine already threads pages functionally and never
donates.
"""

from __future__ import annotations

import json
import math
import struct
from typing import Dict, List, Sequence, Tuple


class OutOfPages(Exception):
    """Raised by :meth:`PageAllocator.alloc` when the free list cannot
    satisfy the request (all-or-nothing; nothing was allocated)."""


#: KV-page blob container magic ("HoroVod KV pages") — the payload the
#: disaggregated prefill→decode handoff chunk-streams (serve/kv_wire).
KV_BLOB_MAGIC = b"HVKV"
_KV_BLOB_HEADER = struct.Struct(">4sI")   # magic, header-JSON length


#: Physical pages never handed out: page 0, the reserved null sink
#: (inactive lanes and padded prefill rows scatter there). The ONE
#: definition both the live allocator and the router-side static
#: admission math derive from.
RESERVED_NULL_PAGES = 1


def allocatable_pages(num_pages: int) -> int:
    """Pages the allocator can actually grant (the capacity both
    :class:`PageAllocator` and the fleet router's static
    :func:`fits_geometry` check must agree on)."""
    return num_pages - RESERVED_NULL_PAGES


def pages_needed(prompt_len: int, max_new_tokens: int,
                 page_size: int) -> int:
    """Worst-case pages for a request: cache positions
    ``0..prompt_len + max_new_tokens - 2`` are written (the final
    sampled token is never fed back), so the last page slot touched is
    ``(prompt_len + max_new_tokens - 2) // page_size``. Module-level so
    the fleet router's static admission check and the live cache share
    ONE page-math implementation."""
    positions = prompt_len + max_new_tokens - 1
    return max(1, math.ceil(positions / page_size))


def append_rows(table, start, n: int, *, page_size: int, num_pages: int,
                valid=None):
    """The multi-row page-write math every multi-token lane shares —
    the chunked-prefill lane (``n = prefill_chunk`` rows at
    ``start..start+n-1``) and the speculative-decode verify window
    (``n = k+1`` rows at ``t..t+k``). Factored here so the engine's two
    lanes and the tests agree on ONE spelling of the boundary cases
    (rows crossing a page edge, rows past the table's last slot, rows
    masked off per-slot).

    ``table`` is one request's page-table index vector [pps]; ``start``
    the first absolute cache position (scalar, traced or static);
    ``valid`` an optional [n] bool mask (``None`` = all rows valid).
    Returns ``(write_page [n], write_off [n], safe_pos [n])``:

    * ``write_page`` — the physical page per row, or the OOB sentinel
      ``num_pages`` for invalid rows, so every scatter through it uses
      ``mode="drop"`` and an invalid row never touches a real page
      (page 0, the null sink, included);
    * ``write_off`` — the in-page offset per row;
    * ``safe_pos`` — the row's absolute position clipped into
      ``0..Lmax-1`` (what gathered-view scatters index with; invalid
      rows must be redirected to the ``Lmax`` drop index by the
      caller, exactly the prefill lane's spelling).

    Rollback of rejected speculative rows is pure page-table
    arithmetic on top of this: stale rows sit at positions the next
    window either overwrites (same ``write_page/write_off`` math) or
    masks (causal attention never admits a key past its own query), so
    no erasure pass exists — and a shared/COW page is copied BEFORE
    the window writes (the engine's ``_cow_guard`` covers the whole
    ``start..start+n-1`` range), so a rejected row can never have
    touched another holder's page."""
    import jax.numpy as jnp

    rows = jnp.arange(n)
    positions = start + rows
    lmax = table.shape[0] * page_size
    safe_pos = jnp.clip(positions, 0, lmax - 1)
    ok = positions < lmax
    if valid is not None:
        ok = jnp.logical_and(valid, ok)
    write_page = jnp.where(ok, table[safe_pos // page_size], num_pages)
    return write_page, safe_pos % page_size, safe_pos


def fits_geometry(prompt_len: int, max_new_tokens: int, *, max_len: int,
                  page_size: int, capacity: int) -> bool:
    """Whether a request can EVER run on this cache geometry: position
    bound (``prompt + steps <= Lmax``) and total-capacity bound.
    ``capacity`` is the ALLOCATABLE page count (num_pages minus the
    reserved null page). The single feasibility predicate behind both
    :meth:`PagedKVCache.fits` (live engine) and
    :meth:`horovod_tpu.serve.fleet.ServeFleet.submit` (router-side —
    admission control must keep answering while every replica is
    mid-relaunch)."""
    return (prompt_len >= 1 and max_new_tokens >= 1
            and prompt_len + max_new_tokens <= max_len
            and pages_needed(prompt_len, max_new_tokens, page_size)
            <= capacity)


class PageAllocator:
    """Free-list allocator over physical page ids.

    Page ids ``reserved..num_pages-1`` are allocatable; ids below
    ``reserved`` (the null sink page 0, by default) are never handed
    out. Frees push onto the list tail and allocations pop from it
    (LIFO — recently-freed pages are re-used first, which keeps the
    working set of physical pages small). ``alloc`` is all-or-nothing:
    either the full grant or :class:`OutOfPages` with no state change.

    Every held page carries a REFCOUNT (1 at grant): ``retain`` adds a
    holder (a prefix-cache hit mapping the page into another request's
    table), ``release`` drops one and frees only at zero. ``free`` is
    the strict single-holder teardown — it refuses shared pages, which
    is what makes a stray direct free under sharing loud instead of a
    corruption (and why callers outside kvcache.py must use ``release``
    — lint rule HVD013).
    """

    def __init__(self, num_pages: int, reserved: int = 1):
        if num_pages <= reserved:
            raise ValueError(
                f"num_pages ({num_pages}) must exceed reserved "
                f"({reserved})")
        self.num_pages = num_pages
        self.reserved = reserved
        self._free: List[int] = list(range(num_pages - 1, reserved - 1, -1))
        self._held: set = set()
        self._refs: Dict[int, int] = {}

    @property
    def capacity(self) -> int:
        return self.num_pages - self.reserved

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._held)

    @property
    def shared(self) -> int:
        """Pages currently held by MORE than one holder."""
        return sum(1 for c in self._refs.values() if c > 1)

    def refcount(self, page: int) -> int:
        """Holders of ``page`` (0 if not allocated)."""
        return self._refs.get(page, 0)

    def is_shared(self, page: int) -> bool:
        """Whether a write to ``page`` must copy-on-write first."""
        return self._refs.get(page, 0) > 1

    def alloc(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free "
                f"(capacity {self.capacity})")
        grant = [self._free.pop() for _ in range(n)]
        self._held.update(grant)
        for p in grant:
            self._refs[p] = 1
        return grant

    def retain(self, pages: Sequence[int]) -> None:
        """Add one holder to each (already-allocated) page — the
        prefix-cache hit path mapping filled pages into a new request's
        table read-only. All-or-nothing: an unallocated page raises
        with no state change."""
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f"retain of page {p} which is not allocated "
                    "(a prefix hit can only share live pages)")
        for p in pages:
            self._refs[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one holder from each page; a page returns to the free
        list only when its LAST holder releases — a shared page can
        never re-enter the free list while refcount > 0. The ONLY
        page-teardown path callers outside this module may use
        (HVD013)."""
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f"release of page {p} which is not allocated "
                    "(double release, or a reserved/null page id)")
            self._refs[p] -= 1
            if self._refs[p] <= 0:
                del self._refs[p]
                self._held.discard(p)
                self._free.append(p)

    def free(self, pages: Sequence[int]) -> None:
        """Strict single-holder teardown: refuses shared pages (a
        direct free under sharing would yank a page other holders'
        tables still map — exactly the bug class refcounts exist to
        prevent)."""
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f"free of page {p} which is not allocated (double "
                    "free, or a reserved/null page id)")
            if self._refs.get(p, 0) > 1:
                raise ValueError(
                    f"free of page {p} with refcount "
                    f"{self._refs[p]} — shared pages must go through "
                    "release() so remaining holders keep the page")
        for p in pages:
            del self._refs[p]
            self._held.discard(p)
            self._free.append(p)


class PagedKVCache:
    """The device-side page arrays + the allocator + the page math.

    ``params`` may be concrete arrays or ``ShapeDtypeStruct``s (the
    hvdverify registry traces the abstract twin): layer count, heads,
    head_dim, Lmax, and dtype are read off the
    :func:`models.parallel_lm.init_lm_params` pytree. The model's
    position-table length must divide into whole pages — the engine's
    gathered per-request cache is then EXACTLY ``[Lmax, H, D]``, the
    decode lane's shape.

    ``kv_sharding`` (a ``NamedSharding`` whose spec shards the HEAD
    axis over the tensor axis) places the page arrays head-sharded —
    each chip holds ``[num_pages, page_size, H/tp, D]``, the engine's
    TP data plane. Everything HOST-side here (the allocator, refcounts,
    page math) is the replicated control plane: allocation decisions
    are identical on every chip by construction because there is
    exactly one allocator making them.
    """

    def __init__(self, params: Dict, config, *, abstract: bool = False,
                 kv_sharding=None):
        import jax
        import jax.numpy as jnp

        self.config = config
        #: Device placement of the page arrays (None = single-chip).
        self.kv_sharding = kv_sharding
        self.max_len = int(params["pos"].shape[0])
        if self.max_len % config.page_size:
            raise ValueError(
                f"position table length {self.max_len} must be a "
                f"multiple of page_size {config.page_size} (whole-page "
                "logical caches keep the gathered layout identical to "
                "the decode lane's)")
        self.pages_per_seq = self.max_len // config.page_size
        wqkv = params["layers"][0]["wqkv"]
        self.num_heads = int(wqkv.shape[2])
        self.head_dim = int(wqkv.shape[3])
        self.dtype = wqkv.dtype
        self.num_layers = len(params["layers"])
        shape = (config.num_pages, config.page_size, self.num_heads,
                 self.head_dim)
        if abstract:
            mk = lambda: jax.ShapeDtypeStruct(shape, self.dtype)  # noqa: E731
        elif kv_sharding is not None:
            mk = lambda: jax.device_put(jnp.zeros(shape, self.dtype),  # noqa: E731
                                        kv_sharding)
        else:
            mk = lambda: jnp.zeros(shape, self.dtype)  # noqa: E731
        #: Per-layer ``{"k", "v"}`` page arrays — the engine's step
        #: program threads these through WITHOUT donation (a live
        #: request's pages must never be overwritten under it;
        #: tools/hvdverify registers the invariant as forbid_donation).
        self.pages = [{"k": mk(), "v": mk()}
                      for _ in range(self.num_layers)]
        self.allocator = PageAllocator(config.num_pages,
                                       reserved=RESERVED_NULL_PAGES)

    # -------------------------------------------------- copy-on-write

    def cow_page(self, page: int) -> int:
        """Copy-on-write: allocate a fresh page, copy ``page``'s K/V
        contents into it across every layer, drop one holder from the
        original, and return the new (exclusively-held) page id. The
        caller swaps its page-table entry to the returned id. A page
        copy + table swap is the WHOLE cost because the engine threads
        pages functionally and never donates — the original stays
        readable under any in-flight step. Raises :class:`OutOfPages`
        (no state change) when no page is free.

        Under ``kv_sharding`` the scatter runs SPMD: the page row is
        elementwise on the sharded head axis, so every chip copies its
        own H/tp slice of the shared page — one coherent copy across
        shards (the re-``device_put`` pins the invariant even if a
        future jax changes scatter sharding propagation)."""
        (new,) = self.allocator.alloc(1)
        try:
            for layer in self.pages:
                for kv in ("k", "v"):
                    upd = layer[kv].at[new].set(layer[kv][page])
                    if self.kv_sharding is not None:
                        import jax

                        upd = jax.device_put(upd, self.kv_sharding)
                    layer[kv] = upd
        except BaseException:
            self.allocator.free([new])
            raise
        self.allocator.release([page])
        return new

    # --------------------------------------------- export / import (kv)

    def export_pages(self, pages: Sequence[int],
                     num_positions: int) -> bytes:
        """Serialize the finished KV pages covering logical positions
        ``0..num_positions-1`` into ONE deterministic byte blob — the
        payload the disaggregated prefill→decode handoff chunk-streams
        (``serve/kv_wire``). ``pages`` is the request's physical page
        list in LOGICAL order (its page-table prefix); the tiles ship
        as per-layer, per-page ``[page_size, H, D]`` K then V arrays in
        the full logical head layout, so the head-sharded placement
        under tp is an import-side property (the importer re-places
        tiles under its OWN ``kv_sharding``) — exporter and importer
        need not agree on tp degree, only on geometry.

        READ-ONLY: refcounts are untouched, so COW/prefix-shared pages
        export safely under any sharing (the blob is a copy, like any
        other reader of a shared page)."""
        import numpy as np

        from horovod_tpu.serve.transport import FrameError

        ps = self.config.page_size
        need = pages_needed(num_positions, 1, ps) \
            if num_positions >= 1 else 0
        if num_positions < 1 or len(pages) != need:
            raise FrameError(
                f"export of {len(pages)} pages for {num_positions} "
                f"positions — geometry says {need} pages of "
                f"{ps} positions each")
        header = json.dumps({
            "layers": self.num_layers,
            "page_size": ps,
            "heads": self.num_heads,
            "head_dim": self.head_dim,
            "dtype": self.dtype.name,
            "pages": len(pages),
            "positions": int(num_positions),
        }).encode("utf-8")
        parts = [_KV_BLOB_HEADER.pack(KV_BLOB_MAGIC, len(header)), header]
        idx = np.asarray(list(pages), dtype=np.int32)
        for layer in self.pages:
            for kv in ("k", "v"):
                # One gather per layer per K/V: [n, page_size, H, D]
                # tiles in logical page order (fetches the full head
                # axis even when the live array is head-sharded).
                parts.append(np.ascontiguousarray(
                    np.asarray(layer[kv][idx])).tobytes())
        return b"".join(parts)

    def import_pages(self, blob: bytes) -> Tuple[List[int], int]:
        """Inverse of :meth:`export_pages` against THIS cache's
        allocator and page arrays: validates geometry (a blob from a
        different model/page shape is a typed
        :class:`~horovod_tpu.serve.transport.FrameError`, never a
        silent reshape), allocates the pages all-or-nothing
        (:class:`OutOfPages` with no state change when the pool lacks
        room), scatters the tiles in, and returns
        ``(granted_pages, num_positions)`` — the granted ids in logical
        order, ready to prefix a page table. Under ``kv_sharding`` the
        written arrays are re-placed so the tiles land head-sharded on
        this replica's own mesh."""
        import numpy as np

        from horovod_tpu.serve.transport import FrameError

        if len(blob) < _KV_BLOB_HEADER.size:
            raise FrameError(
                f"kv blob of {len(blob)} bytes is shorter than its "
                "header — torn payload")
        magic, hlen = _KV_BLOB_HEADER.unpack_from(blob)
        if magic != KV_BLOB_MAGIC:
            raise FrameError(
                f"bad kv-blob magic {magic!r} — not a HVKV payload")
        end = _KV_BLOB_HEADER.size + hlen
        if len(blob) < end:
            raise FrameError("kv blob torn inside its header")
        try:
            h = json.loads(blob[_KV_BLOB_HEADER.size:end].decode("utf-8"))
            n, positions = int(h["pages"]), int(h["positions"])
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            raise FrameError(f"undecodable kv-blob header: {e!r}"
                             ) from None
        ps = self.config.page_size
        want = {"layers": self.num_layers, "page_size": ps,
                "heads": self.num_heads, "head_dim": self.head_dim,
                "dtype": self.dtype.name}
        got = {k: h.get(k) for k in want}
        if got != want:
            raise FrameError(
                f"kv blob geometry {got} does not match this cache "
                f"{want} — cross-model/cross-geometry import refused")
        if positions < 1 or n != pages_needed(positions, 1, ps):
            raise FrameError(
                f"kv blob claims {n} pages for {positions} positions — "
                "inconsistent page math")
        tile = ps * self.num_heads * self.head_dim
        dt = np.dtype(self.dtype)
        total = end + self.num_layers * 2 * n * tile * dt.itemsize
        if len(blob) != total:
            raise FrameError(
                f"kv blob is {len(blob)} bytes, geometry says {total} "
                "— torn or padded payload")
        grant = self.allocator.alloc(n)     # all-or-nothing; OutOfPages
        import jax
        import jax.numpy as jnp

        idx = jnp.asarray(grant, dtype=jnp.int32)
        off = end
        step = n * tile * dt.itemsize
        try:
            for layer in self.pages:
                for kv in ("k", "v"):
                    tiles = np.frombuffer(
                        blob[off:off + step], dtype=dt).reshape(
                            n, ps, self.num_heads, self.head_dim)
                    off += step
                    upd = layer[kv].at[idx].set(jnp.asarray(tiles))
                    if self.kv_sharding is not None:
                        upd = jax.device_put(upd, self.kv_sharding)
                    layer[kv] = upd
        except BaseException:
            self.allocator.free(grant)
            raise
        return grant, positions

    # ------------------------------------------------------- page math

    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case pages for a request — :func:`pages_needed` over
        this cache's page size."""
        return pages_needed(prompt_len, max_new_tokens,
                            self.config.page_size)

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Whether the request can EVER run — :func:`fits_geometry`
        over this cache's geometry. Failing this is a hard reject, not
        a queue."""
        return fits_geometry(prompt_len, max_new_tokens,
                             max_len=self.max_len,
                             page_size=self.config.page_size,
                             capacity=self.allocator.capacity)

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Admission control (reserve discipline): admit only when the
        worst case is allocatable RIGHT NOW, so an admitted request can
        always run to completion without eviction."""
        return (self.pages_needed(prompt_len, max_new_tokens)
                <= self.allocator.available)

    # ---------------------------------------------------------- stats

    def occupancy(self) -> float:
        """Fraction of allocatable pages currently held (0..1)."""
        return self.allocator.in_use / max(1, self.allocator.capacity)

    def stats(self) -> Dict[str, float]:
        return {
            "pages_total": self.allocator.capacity,
            "pages_in_use": self.allocator.in_use,
            "pages_free": self.allocator.available,
            "pages_shared": self.allocator.shared,
            "occupancy": self.occupancy(),
        }
