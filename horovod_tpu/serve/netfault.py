"""Deterministic network fault injection at the fleet-transport seam.

A multi-host fleet's new failure modes are NETWORK failures — a NIC
partition, a link so degraded every frame trickles, a reply delayed
past its deadline, a frame torn mid-write when a host drops — and a
recovery path only exercised by real outages is an untested one. This
module makes every one of them injectable on loopback TCP, CI-fast and
bit-deterministic: :class:`FaultableSocket` wraps a real connected
socket (plugged in through ``RpcClient(sock_wrap=...)`` on the router
side, or wrapped around an accepted connection on the worker side) and
misbehaves according to a shared :class:`NetFaults` state, one per
HOST — which is exactly what makes a *host* a failure domain: every
connection to the host degrades together, the way a real NIC loss
takes out all of them at once.

Fault modes (all composable, all resolving as the PR-13 typed
:class:`~horovod_tpu.serve.transport.TransportError` taxonomy, never a
hang):

* **partition** (``NetFaults.partition(secs)``): the link goes dark —
  reads see silence (``socket.timeout`` per poll slice, so the
  transport's deadline discipline fires :class:`DeadlineExceeded
  <horovod_tpu.serve.transport.DeadlineExceeded>` if the window
  outlasts the budget) and writes are black-holed. When the window
  ends, every connection that predates the partition raises
  ``ConnectionResetError`` on its next operation — the **half-open
  connection after a host returns**: the peer's TCP state is gone, and
  the transport maps the reset to :class:`ConnectionLost
  <horovod_tpu.serve.transport.ConnectionLost>`. Connections opened
  AFTER the window (a relaunch) are clean. ``secs=None`` partitions
  forever (the host never comes back; detection is then purely the
  deadline's).
* **delay** (``delay_s``): every read waits ``delay_s`` first — a
  congested link; a delay past the caller's recv budget resolves as
  that budget's ``socket.timeout`` (→ ``DeadlineExceeded`` upstream).
* **trickle** (``trickle_bytes``): reads return at most N bytes per
  call — a degraded link. A frame that keeps trickling *within* its
  deadline still completes (the transport's contract); one that cannot
  hits the deadline.
* **tear** (``tear_send_frame``): the Nth ``sendall`` through the
  socket writes only half its bytes, then the connection dies — the
  kill-mid-write shape, injected mid-FRAME so the peer's codec must
  resolve it as a torn :class:`FrameError
  <horovod_tpu.serve.transport.FrameError>`. ONE-SHOT: firing clears
  the armed fault, so a retry's fresh connection (the params-push
  resume lane) proceeds clean instead of tearing forever.

The wrapper intercepts only the calls the transport makes (``recv``,
``sendall``, ``settimeout``, ``close``); everything else delegates.
Timing note: fault windows run on ``time.monotonic`` (the same clock
the transport's deadlines use), independent of the fleet's injectable
test clock — a partition is wall-clock physics, like heartbeat file
mtimes.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional


class NetFaults:
    """Shared, mutable fault state for every connection to one host.

    The serving fault grammar's ``partition:host=H,at=T[,secs=S]``
    resolves to ``fleet._hosts[H].faults.partition(S)``; tests drive
    the other knobs directly. Thread-safe: the worker's RPC thread and
    the router poke sockets concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        #: bumped on every partition; sockets born before the current
        #: epoch raise ConnectionResetError once the window ends (the
        #: half-open-after-return shape).
        self.epoch = 0
        self.partition_until = 0.0
        self.delay_s = 0.0
        self.trickle_bytes = 0
        #: 1-based index of the sendall call to tear on (None = off).
        self.tear_send_frame: Optional[int] = None

    def partition(self, secs: Optional[float] = None) -> None:
        """Open a partition window now: ``secs`` seconds (None =
        forever — the host never returns)."""
        with self._lock:
            self.epoch += 1
            self.partition_until = (float("inf") if secs is None
                                    else time.monotonic() + float(secs))

    def partitioned(self) -> bool:
        return time.monotonic() < self.partition_until

    def wrap(self, sock: socket.socket) -> "FaultableSocket":
        """The ``RpcClient(sock_wrap=...)`` / worker-accept hook."""
        return FaultableSocket(sock, self)


class FaultableSocket:
    """A connected socket that misbehaves per its :class:`NetFaults`.

    Drop-in at the transport seam: implements the exact surface
    ``serve/transport.py`` touches and delegates the rest."""

    def __init__(self, sock: socket.socket, faults: NetFaults):
        self._sock = sock
        self._faults = faults
        self._born_epoch = faults.epoch
        self._timeout = sock.gettimeout()
        self._sends = 0

    # ------------------------------------------------ fault gates

    def _poll_budget(self) -> float:
        t = self._timeout
        return 0.25 if t is None else min(float(t), 0.25)

    def _gate(self) -> None:
        """Raise the active fault's failure shape, if any (shared by
        reads and writes for the partition/half-open modes)."""
        f = self._faults
        if f.partitioned():
            raise _Partitioned()
        if f.epoch > self._born_epoch:
            raise ConnectionResetError(
                "half-open connection: the peer host was partitioned "
                "and has returned — this connection's state is gone")

    # ------------------------------------------------ intercepted API

    def settimeout(self, t) -> None:
        self._timeout = t
        self._sock.settimeout(t)

    def gettimeout(self):
        return self._timeout

    def recv(self, n: int, *flags) -> bytes:
        f = self._faults
        try:
            self._gate()
        except _Partitioned:
            # Silence on the wire: wait out one poll slice and time
            # out, exactly like a link that stopped delivering.
            time.sleep(self._poll_budget())
            raise socket.timeout("partitioned") from None
        if f.delay_s:
            t = self._timeout
            if t is not None and f.delay_s >= float(t):
                time.sleep(float(t))
                raise socket.timeout("delayed past the recv budget")
            time.sleep(f.delay_s)
        if f.trickle_bytes:
            n = min(n, f.trickle_bytes)
        return self._sock.recv(n, *flags)

    def sendall(self, data: bytes) -> None:
        f = self._faults
        try:
            self._gate()
        except _Partitioned:
            return   # black hole: the kernel "accepted" it, the wire ate it
        if f.tear_send_frame is not None:
            self._sends += 1
            fire = False
            with f._lock:
                if f.tear_send_frame is not None \
                        and self._sends >= f.tear_send_frame:
                    # One-shot: the armed tear is consumed by the
                    # socket that fires it (a resumed transfer's fresh
                    # connection must not re-tear).
                    f.tear_send_frame = None
                    fire = True
            if fire:
                self._sock.sendall(data[:max(1, len(data) // 2)])
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise ConnectionResetError(
                    "torn mid-frame by fault injection (writer died "
                    "half-way through the frame)")
        self._sock.sendall(data)

    def close(self) -> None:
        self._sock.close()

    def __getattr__(self, name):
        return getattr(self._sock, name)


class _Partitioned(Exception):
    """Internal control flow for the partition gate."""


__all__ = ["FaultableSocket", "NetFaults"]
