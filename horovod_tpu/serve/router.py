"""Fleet routing policy: least-loaded replica selection + the overload
hint.

Pure functions over the fleet's replica objects — the router never
touches device arrays and holds no state of its own, so
:class:`~horovod_tpu.serve.fleet.ServeFleet` (which owns the admission
queue and the replica lifecycle) is the single writer and these
policies are unit-testable in isolation.

Routing is least-loaded with a deliberate key order:

1. **free decode slots** (desc) — the resource a new request occupies
   first; a replica with idle lanes finishes new work soonest;
2. **page occupancy** (asc) — the eviction-pressure tiebreak: between
   two replicas with equal lanes, the one with more free KV pages is
   less likely to evict-recompute;
3. **dispatched-but-unfinished count** (asc) — breaks cold-start ties
   (all replicas idle) so a burst spreads round-robin instead of
   piling onto replica 0;
4. **replica id** — total order, so routing is deterministic for the
   bit-exactness pins.

With prefix caching on, a request carrying a route key (the stable
hash of its page-aligned prompt prefix — :func:`~horovod_tpu.serve.
prefix.prefix_route_key`) ranks its **rendezvous weight** (desc)
AHEAD of all four: requests sharing a prefix land on the replica that
already holds its pages — one cold prefill per unique prefix per
REPLICA — and the load keys only break exact-weight ties.
Highest-random-weight hashing keeps the affinity stateless and
deterministic: when the prefix's home replica dies (or is saturated —
it simply drops out of the eligible set), the next-ranked survivor
becomes the home, with no routing table to migrate.

A replica is only *eligible* when healthy and when the request fits
under its in-flight limit right now — the router holds backlog at the
FLEET level (one queue to shed from, cheaper redispatch, better
balancing) instead of deep-queueing inside replicas.
"""

from __future__ import annotations

from typing import Optional, Sequence


def replica_load(rep) -> dict:
    """One replica's routing-relevant load (also the ``stats()``
    per-replica cell): free decode slots, page occupancy, and the
    dispatched-but-unfinished request count."""
    eng = rep.engine
    if eng is None:
        return {"free_slots": 0, "occupancy": 1.0,
                "in_flight": len(rep.assigned)}
    return {
        "free_slots": eng._free_slots(),
        "occupancy": eng.cache.occupancy(),
        "in_flight": len(rep.assigned),
    }


def eligible(rep, req) -> bool:
    """May ``req`` be dispatched to ``rep`` right now? Healthy,
    INITIALIZED (a wire-init worker has no weights until its first
    params push commits — ``rep.version`` is None until then),
    ACCEPTING (a replica mid-rolling-update is draining: routing new
    work to it would make the drain a livelock), VERSION-compatible
    (a request already streaming under params version V may only
    continue on a replica serving exactly V — the version pin that
    makes a mid-stream weight mix impossible), the geometry admits the
    request at all, there is in-flight headroom
    (dispatched-but-unfinished stays under the engine's in-flight
    limit, so the router never deep-queues into a replica), and the
    engine's OWN bounded queue — a standalone-engine knob the fleet
    config may still carry — has room. The last check matters: an
    engine-side queue reject is TERMINAL, while the router's contract
    is that a backlogged request WAITS at the fleet head until a
    replica frees up."""
    if not rep.healthy or rep.engine is None:
        return False
    if getattr(rep, "version", 1) is None \
            or not getattr(rep, "accepting", True):
        return False
    req_version = getattr(req, "version", None)
    if req_version is not None and rep.version != req_version:
        return False
    eng = rep.engine
    if not eng.cache.fits(req.prompt_len, req.max_new_tokens):
        return False
    if len(rep.assigned) >= eng.config.in_flight_limit:
        return False
    c = eng.config
    return not c.max_queue or len(eng.scheduler.queue) < c.max_queue


def pick_replica(replicas: Sequence, req,
                 route_key: Optional[str] = None) -> Optional[object]:
    """The least-loaded eligible replica for ``req`` (None = every
    replica is down or saturated; the fleet queue's head WAITS — no
    skip — preserving arrival order the same way the scheduler's
    reserve admission does). ``route_key`` (prefix caching on, prompt
    at least one full page) ranks the rendezvous weight ahead of the
    load keys — see the module docstring's key-order rationale."""
    from horovod_tpu.serve.prefix import rendezvous_rank

    candidates = [r for r in replicas if eligible(r, req)]
    if not candidates:
        return None
    loads = {r.id: replica_load(r) for r in candidates}

    def load_key(r):
        return (-loads[r.id]["free_slots"],
                loads[r.id]["occupancy"],
                loads[r.id]["in_flight"],
                r.id)

    if route_key is None:
        return min(candidates, key=load_key)
    return min(candidates, key=lambda r: (
        (-rendezvous_rank(route_key, r.id),) + load_key(r)))


def retry_after_hint(backlog: int, healthy_slots: int,
                     service_samples: Sequence[float],
                     floor: float) -> float:
    """Advisory seconds-until-retry for an overloaded rejection.

    Little's-law flavored: the backlog ahead of the client, divided by
    the fleet's current parallel service capacity, times the observed
    mean request service time (admit -> finish). With no finished
    requests yet (cold start) the floor alone is returned — an honest
    "soon" rather than a made-up number."""
    if not service_samples or healthy_slots < 1:
        return floor
    mean_service = sum(service_samples) / len(service_samples)
    return max(floor, (backlog + 1) * mean_service / healthy_slots)
