"""Shared chunk-stream framing: ONE framing/CRC/resume implementation.

PR 15 built the chunked transfer discipline for weights — manifest
first (whole-artifact sha256, sizes), then bounded chunks each carrying
its offset and its OWN crc32, assembled contiguously so
resume-from-offset after a torn transfer is exact by construction, and
nothing is ever loadable until the whole-artifact digest verifies. The
disaggregated-serving KV handoff needs the identical discipline for a
different payload (finished KV pages instead of weights), so the
framing lives HERE and both consumers — :mod:`~horovod_tpu.serve.
params_wire` (weights, assembling to a crash-safe temp file) and
:mod:`~horovod_tpu.serve.kv_wire` (KV pages, assembling to memory) —
share one spelling of every boundary case:

* :func:`make_manifest` leads every transfer: stream kind, payload
  version, the whole-blob sha256, total/chunk byte counts (plus any
  consumer ``extra`` fields, e.g. the params manifest's per-leaf
  specs);
* :func:`make_chunk` / :func:`check_chunk` frame each chunk with its
  offset and its own crc32 — a truncated, mis-ordered or version-mixed
  chunk is a typed :class:`~horovod_tpu.serve.transport.FrameError`, a
  bit flip a typed :class:`~horovod_tpu.serve.transport.ChecksumError`
  (caught per chunk, so a sender retries one chunk, not the artifact);
* :class:`BufferAssembler` is the in-memory receiver half (contiguity
  enforced, digest-verified commit) for transient payloads that never
  touch a filesystem; the file-backed, crash-safe variant is
  :class:`params_wire.ArtifactAssembler
  <horovod_tpu.serve.params_wire.ArtifactAssembler>`, built on the
  same check functions.

The refactor contract (pinned in tests/test_chunk_stream.py): the
params consumer's manifests and chunks are BYTE-IDENTICAL to the
pre-refactor PR-15 forms — key order included, since manifests travel
inside JSON frames whose bytes the weight-roll records digest.

Stdlib-only, like the frame codec itself: the protocol-stub test
worker (``python -S``) runs the identical assembly/verify path.
"""

from __future__ import annotations

import base64
import hashlib
import zlib
from typing import Dict, Optional, Tuple

from horovod_tpu.serve.transport import ChecksumError, FrameError

#: Default transfer chunk size. Base64 expansion (x4/3) must keep a
#: chunk frame well under transport.MAX_FRAME (16 MiB).
DEFAULT_CHUNK_BYTES = 1 << 20


def sha256_hex(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


# ------------------------------------------------------------- manifest


def make_manifest(blob: bytes, *, kind: str, version: int,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                  extra: Optional[Dict] = None) -> Dict:
    """The leading frame of every transfer: what the receiver must end
    up holding (kind, version, whole-blob sha256, sizes). ``extra``
    appends consumer fields AFTER the shared ones — key order is part
    of the wire contract (the params consumer's manifests must stay
    byte-identical to their PR-15 form)."""
    if version < 1:
        raise ValueError(f"artifact version must be >= 1, got {version}")
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    total = len(blob)
    manifest = {
        "kind": kind,
        "version": int(version),
        "sha256": sha256_hex(blob),
        "total_bytes": total,
        "chunk_bytes": int(chunk_bytes),
        "num_chunks": max(1, -(-total // chunk_bytes)),
    }
    if extra:
        manifest.update(extra)
    return manifest


def check_manifest(manifest: Dict,
                   kind: Optional[str] = None) -> None:
    """Validate a received manifest's internal consistency (typed
    :class:`FrameError` on anything off). ``kind`` additionally pins
    the stream kind — a KV receiver fed a params manifest (or the
    reverse) must fail loudly at the manifest, not at import."""
    try:
        version = int(manifest["version"])
        sha = manifest["sha256"]
        total = int(manifest["total_bytes"])
        cb = int(manifest["chunk_bytes"])
        n = int(manifest["num_chunks"])
    except (KeyError, TypeError, ValueError) as e:
        raise FrameError(f"malformed transfer manifest: {e!r}") from None
    if version < 1 or total < 0 or cb < 1 \
            or n != max(1, -(-total // cb)) \
            or not (isinstance(sha, str) and len(sha) == 64):
        raise FrameError(f"inconsistent transfer manifest: {manifest!r}")
    if kind is not None and manifest.get("kind") != kind:
        raise FrameError(
            f"transfer manifest kind {manifest.get('kind')!r} is not "
            f"{kind!r} — wrong stream routed to this receiver")


def chunk_span(manifest: Dict, index: int) -> Tuple[int, int]:
    """``(offset, size)`` of chunk ``index`` under the manifest's
    geometry."""
    cb = int(manifest["chunk_bytes"])
    total = int(manifest["total_bytes"])
    offset = index * cb
    return offset, min(cb, total - offset)


# --------------------------------------------------------------- chunks


def make_chunk(blob: bytes, manifest: Dict, index: int) -> Dict:
    """One bounded transfer chunk: offset + size + per-chunk crc32 +
    base64 payload (the frame codec carries JSON)."""
    if not 0 <= index < int(manifest["num_chunks"]):
        raise FrameError(
            f"chunk index {index} outside 0..{manifest['num_chunks'] - 1}")
    offset, size = chunk_span(manifest, index)
    raw = blob[offset:offset + size]
    return {
        "version": int(manifest["version"]),
        "index": int(index),
        "offset": offset,
        "size": size,
        "crc32": zlib.crc32(raw),
        "data": base64.b64encode(raw).decode("ascii"),
    }


def check_chunk(manifest: Dict, chunk: Dict) -> Tuple[int, bytes]:
    """Validate one received chunk against the transfer's manifest;
    returns ``(offset, raw_bytes)``. Every way the chunk can be wrong
    is a TYPED error — a truncated payload, a mis-indexed or
    version-mixed chunk is :class:`FrameError`; payload bytes that do
    not match their own crc32 are :class:`ChecksumError` (the
    bit-corruption shape the whole-artifact digest would also catch,
    caught here per chunk so the sender retries one chunk, not the
    artifact)."""
    if not isinstance(chunk, dict):
        raise FrameError(f"chunk is not a mapping: {type(chunk).__name__}")
    try:
        version = int(chunk["version"])
        index = int(chunk["index"])
        offset = int(chunk["offset"])
        size = int(chunk["size"])
        crc = int(chunk["crc32"])
        data = chunk["data"]
    except (KeyError, TypeError, ValueError) as e:
        raise FrameError(f"malformed chunk: {e!r}") from None
    if version != int(manifest["version"]):
        raise FrameError(
            f"chunk carries version {version}, transfer manifest says "
            f"{manifest['version']} — version mix on the wire")
    if not 0 <= index < int(manifest["num_chunks"]):
        raise FrameError(
            f"chunk index {index} outside 0..{manifest['num_chunks'] - 1}")
    want_offset, want_size = chunk_span(manifest, index)
    if offset != want_offset or size != want_size:
        raise FrameError(
            f"chunk {index} claims offset/size {offset}/{size}, manifest "
            f"geometry says {want_offset}/{want_size}")
    try:
        raw = base64.b64decode(data, validate=True)
    except Exception as e:
        raise FrameError(f"chunk {index}: undecodable payload: {e}"
                         ) from None
    if len(raw) != size:
        raise FrameError(
            f"chunk {index}: payload is {len(raw)} bytes, header says "
            f"{size} — truncated or padded chunk")
    if zlib.crc32(raw) != crc:
        raise ChecksumError(
            f"chunk {index}: crc32 mismatch on {size} payload bytes — "
            "corrupted in flight or at the source")
    return offset, raw


# ------------------------------------------------------------ assembler


class BufferAssembler:
    """In-memory assemble + digest-verify: the receiver half for
    transient payloads (the KV handoff) that must never touch a
    filesystem. Same protocol as the file-backed
    :class:`~horovod_tpu.serve.params_wire.ArtifactAssembler` —
    :meth:`begin` arms one transfer and returns the verified resume
    offset, :meth:`write_chunk` enforces contiguity (the resume
    contract is a single verified prefix), :meth:`commit` verifies the
    whole-blob sha256 and only then hands the bytes out (a torn or
    corrupted transfer can never be imported, partially or otherwise).

    A re-``begin`` with the SAME (version, sha256) manifest keeps the
    assembled prefix — resume-from-offset after a torn transfer; any
    other manifest drops it (a new payload starts clean)."""

    def __init__(self, kind: Optional[str] = None):
        self.kind = kind
        self.manifest: Optional[Dict] = None
        self._buf = bytearray()

    @property
    def have_bytes(self) -> int:
        return len(self._buf)

    def begin(self, manifest: Dict) -> int:
        """Arm the assembler for one transfer; returns ``have_bytes``
        — the verified prefix of THIS (version, sha256) payload
        already assembled, floored to a whole chunk, so the sender
        resumes from there instead of resending the blob."""
        check_manifest(manifest, kind=self.kind)
        prev = self.manifest
        if prev is None or prev["sha256"] != manifest["sha256"] \
                or int(prev["version"]) != int(manifest["version"]):
            self._buf = bytearray()
        self.manifest = dict(manifest)
        cb = int(manifest["chunk_bytes"])
        have = min((len(self._buf) // cb) * cb,
                   int(manifest["total_bytes"]))
        # A partial trailing chunk (a tear mid-write) is never trusted:
        # truncate back to the last whole-chunk boundary.
        del self._buf[have:]
        return have

    def write_chunk(self, chunk: Dict) -> int:
        """Validate + append one chunk; returns the new ``have_bytes``.
        Chunks must arrive contiguously (``offset == have``)."""
        if self.manifest is None:
            raise FrameError("write_chunk before begin()")
        offset, raw = check_chunk(self.manifest, chunk)
        if offset != len(self._buf):
            raise FrameError(
                f"non-contiguous chunk: offset {offset} but only "
                f"{len(self._buf)} bytes assembled — resume must "
                "continue the verified prefix")
        self._buf.extend(raw)
        return len(self._buf)

    def commit(self) -> Tuple[bytes, str]:
        """Digest-verify the assembled blob and return
        ``(blob, sha256)``. An incomplete assembly is
        :class:`FrameError`; a digest mismatch DROPS the buffer and
        raises :class:`ChecksumError` — there is no partial import, and
        the next attempt starts clean."""
        if self.manifest is None:
            raise FrameError("commit before begin()")
        m = self.manifest
        if len(self._buf) != int(m["total_bytes"]):
            raise FrameError(
                f"commit of an incomplete transfer: {len(self._buf)}/"
                f"{m['total_bytes']} bytes assembled")
        blob = bytes(self._buf)
        sha = sha256_hex(blob)
        if sha != m["sha256"]:
            self._buf = bytearray()
            raise ChecksumError(
                f"whole-blob digest mismatch: assembled {sha}, "
                f"manifest says {m['sha256']} — refusing the torn/"
                "corrupted transfer (no partial import)")
        return blob, sha

    def abort(self) -> None:
        """Drop the in-progress buffer (a transfer abandoned by the
        sender; a plain retry re-``begin``\\ s and keeps the prefix)."""
        self._buf = bytearray()
        self.manifest = None


__all__ = [
    "BufferAssembler", "DEFAULT_CHUNK_BYTES", "check_chunk",
    "check_manifest", "chunk_span", "make_chunk", "make_manifest",
    "sha256_hex",
]
