"""Vectorized per-slot sampling for the serving engine.

One jitted [N, V] sampler covers every lane of a step (decode slots +
the prefill lane's first token) with PER-SLOT knobs, so requests with
different temperatures/top-k share the one compiled program:

* ``temperature == 0`` — greedy: ``argmax(logits.astype(float32))``,
  the EXACT spelling ``models.parallel_lm.lm_decode`` uses, which is
  what makes the engine's greedy stream token-identical to the decode
  lane (pinned in tests/test_serve_engine.py);
* ``temperature > 0`` — categorical over ``logits / temperature``,
  optionally top-k-masked (``top_k <= 0`` = full vocab; ties at the
  k-th logit are all kept — the mask is a >= threshold, standard
  top-k-with-ties semantics).

Keys are **position-folded**: token i of request r draws from
``fold_in(PRNGKey(seed_r), i)`` where i indexes the request's FULL
generation stream. No sampler state exists between steps, so a request
evicted and recomputed (scheduler lazy mode) re-draws the identical
tokens — sampling is a pure function of (seed, position, logits).
This intentionally differs from ``lm_decode``'s single split-chain key
(which is batch-coupled: one key drives all B rows); only the greedy
path is pinned token-exact against the decode lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _sample_one(logits, temperature, top_k, seed, position):
    """One slot: logits [V] f32 -> token (int32 scalar)."""
    v = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1)

    key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    k = jnp.clip(jnp.where(top_k <= 0, v, top_k), 1, v)
    # Descending sort once; the k-th value is the keep threshold.
    thresh = jnp.sort(logits)[::-1][k - 1]
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    masked = jnp.where(logits >= thresh, logits / safe_t, -jnp.inf)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled,
                     greedy).astype(jnp.int32)


@jax.jit
def sample_tokens(logits, temperature, top_k, seeds, positions):
    """Per-slot sampling: logits [N, V] (any float dtype), temperature
    [N] f32, top_k [N] i32, seeds [N] i32/u32, positions [N] i32 ->
    tokens [N] i32. Rows are independent — inactive lanes sample
    garbage that the host discards."""
    # f32 BEFORE any arithmetic: the greedy path must argmax the exact
    # tensor lm_decode argmaxes.
    logits = logits.astype(jnp.float32)
    return jax.vmap(_sample_one)(logits,
                                 temperature.astype(jnp.float32),
                                 top_k.astype(jnp.int32),
                                 seeds.astype(jnp.uint32),
                                 positions.astype(jnp.uint32))
