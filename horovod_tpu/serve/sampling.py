"""Vectorized per-slot sampling for the serving engine.

One jitted [N, V] sampler covers every lane of a step (decode slots +
the prefill lane's first token) with PER-SLOT knobs, so requests with
different temperatures/top-k share the one compiled program:

* ``temperature == 0`` — greedy: ``argmax(logits.astype(float32))``,
  the EXACT spelling ``models.parallel_lm.lm_decode`` uses, which is
  what makes the engine's greedy stream token-identical to the decode
  lane (pinned in tests/test_serve_engine.py);
* ``temperature > 0`` — categorical over ``logits / temperature``,
  optionally top-k-masked (``top_k <= 0`` = full vocab; ties at the
  k-th logit are all kept — the mask is a >= threshold, standard
  top-k-with-ties semantics).

Keys are **position-folded**: token i of request r draws from
``fold_in(PRNGKey(seed_r), i)`` where i indexes the request's FULL
generation stream. No sampler state exists between steps, so a request
evicted and recomputed (scheduler lazy mode) re-draws the identical
tokens — sampling is a pure function of (seed, position, logits).
This intentionally differs from ``lm_decode``'s single split-chain key
(which is batch-coupled: one key drives all B rows); only the greedy
path is pinned token-exact against the decode lane.

**Speculative decoding** adds two surfaces, both keyed by the same
(seed, absolute output position) scheme with DOMAIN-SEPARATED folds so
the draft's randomness never collides with the target's:

* :func:`draft_sample_tokens` — the in-step draft proposal (greedy
  when ``temperature == 0``; otherwise a draw from the DRAFT's own
  top-k/temperature distribution, the ``q`` the rejection test needs
  proposals to actually follow);
* :func:`speculative_accept` — the host-side acceptance rule for one
  slot. Greedy: keep the longest prefix where draft and target
  argmaxes agree, then the target's token at the first mismatch (the
  correction) or one bonus token — every emitted token is a target
  argmax of its true prefix, which is the bit-exactness proof.
  ``temperature > 0``: standard rejection sampling (accept ``d_i``
  iff ``u_i * q_i(d_i) <= p_i(d_i)``; on reject, resample from the
  normalized residual ``max(p - q, 0)``), every draw position-folded,
  so eviction-recompute and fleet redispatch re-draw identically under
  the SAME window alignment (greedy is alignment-independent; sampled
  streams are same-seed deterministic — docs/serving.md spells out
  the clean-vs-faulted caveat).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: Domain separators folded into the position key so the draft's
#: proposal draw, the acceptance uniform and the residual resample are
#: three independent streams per (seed, position).
DRAFT_FOLD = 0x5D_01
ACCEPT_FOLD = 0x5D_02
RESIDUAL_FOLD = 0x5D_03


def _masked_logits(logits, temperature, top_k):
    """Top-k + temperature masking shared by every sampling surface:
    logits [V] f32 -> masked logits [V] (kept entries divided by the
    temperature, the rest -inf; ties at the k-th logit all kept)."""
    v = logits.shape[0]
    k = jnp.clip(jnp.where(top_k <= 0, v, top_k), 1, v)
    # Descending sort once; the k-th value is the keep threshold.
    thresh = jnp.sort(logits)[::-1][k - 1]
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    return jnp.where(logits >= thresh, logits / safe_t, -jnp.inf)


def _sample_one(logits, temperature, top_k, seed, position):
    """One slot: logits [V] f32 -> token (int32 scalar)."""
    greedy = jnp.argmax(logits, axis=-1)

    key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    masked = _masked_logits(logits, temperature, top_k)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled,
                     greedy).astype(jnp.int32)


@jax.jit
def sample_tokens(logits, temperature, top_k, seeds, positions):
    """Per-slot sampling: logits [N, V] (any float dtype), temperature
    [N] f32, top_k [N] i32, seeds [N] i32/u32, positions [N] i32 ->
    tokens [N] i32. Rows are independent — inactive lanes sample
    garbage that the host discards."""
    # f32 BEFORE any arithmetic: the greedy path must argmax the exact
    # tensor lm_decode argmaxes.
    logits = logits.astype(jnp.float32)
    return jax.vmap(_sample_one)(logits,
                                 temperature.astype(jnp.float32),
                                 top_k.astype(jnp.int32),
                                 seeds.astype(jnp.uint32),
                                 positions.astype(jnp.uint32))


def _draft_one(logits, temperature, top_k, seed, position):
    """One slot's draft proposal: logits [V] f32 -> token. Greedy at
    ``temperature == 0`` (the bit-exact lane — proposal quality only
    moves the accept rate, never a token); otherwise a draw from the
    draft's OWN masked distribution under the ``DRAFT_FOLD``-separated
    position key, so the rejection test upstream sees proposals that
    genuinely follow ``q``."""
    greedy = jnp.argmax(logits, axis=-1)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), position),
        DRAFT_FOLD)
    masked = _masked_logits(logits, temperature, top_k)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled,
                     greedy).astype(jnp.int32)


def draft_sample_tokens(logits, temperature, top_k, seeds, positions):
    """Vectorized draft proposals, traced INSIDE the compiled serve
    step (not jitted here — the propose scan feeds each proposal to
    the next draft step): logits [N, V] -> tokens [N] i32."""
    logits = logits.astype(jnp.float32)
    return jax.vmap(_draft_one)(logits,
                                temperature.astype(jnp.float32),
                                top_k.astype(jnp.int32),
                                seeds.astype(jnp.uint32),
                                positions.astype(jnp.uint32))


def speculative_accept(target_logits, draft_toks, draft_logits, *,
                       temperature: float, top_k: int, seed: int,
                       position0: int):
    """The acceptance rule for ONE slot's speculative tick.

    ``target_logits`` [w, V] are the verify pass's logits (row i draws
    the token at output position ``position0 + i``); ``draft_toks``
    [w-1] and ``draft_logits`` [w-1, V] are the draft's proposals for
    rows 1..w-1's PREDECESSOR positions (proposal i competes for
    output position ``position0 + i``). Returns the emitted tokens —
    between 1 (immediate mismatch/reject: the correction alone) and
    ``w`` (every proposal accepted + the bonus).

    Greedy (``temperature <= 0``): emit ``argmax(float32 row)`` — the
    exact :func:`sample_tokens` greedy spelling — walking rows while
    the draft's proposal matches. Bit-identical to the non-speculative
    engine by construction: the emitted token at any position is the
    target's argmax given exactly the previously emitted prefix, no
    matter what the draft proposed or where tick boundaries fell.

    ``temperature > 0``: Leviathan-style rejection sampling. Proposal
    ``d_i ~ q_i`` is accepted iff ``u_i * q_i(d_i) <= p_i(d_i)`` with
    ``u_i`` drawn under the ``ACCEPT_FOLD`` position key; on rejection
    the correction comes from the normalized residual ``max(p_i - q_i,
    0)`` under the ``RESIDUAL_FOLD`` key, preserving the target
    distribution exactly. The bonus token (all proposals accepted) and
    the ``w == 1`` degenerate tick use :func:`_sample_one` verbatim —
    the NON-speculative draw at that position, same key and all."""
    tl = jnp.asarray(target_logits).astype(jnp.float32)
    w = tl.shape[0]
    if temperature <= 0:
        tgt = np.asarray(jnp.argmax(tl, axis=-1))
        out = []
        for i in range(w):
            out.append(int(tgt[i]))
            if i == w - 1 or int(draft_toks[i]) != int(tgt[i]):
                break
        return out

    dl = jnp.asarray(draft_logits).astype(jnp.float32)
    out = []
    for i in range(w - 1):
        d = int(draft_toks[i])
        p = np.asarray(jax.nn.softmax(
            _masked_logits(tl[i], temperature, top_k)))
        q = np.asarray(jax.nn.softmax(
            _masked_logits(dl[i], temperature, top_k)))
        pos_key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                     np.uint32(position0 + i))
        u = float(jax.random.uniform(
            jax.random.fold_in(pos_key, ACCEPT_FOLD)))
        if u * float(q[d]) <= float(p[d]):
            out.append(d)
            continue
        residual = np.maximum(p - q, 0.0)
        total = float(residual.sum())
        # total == 0 means p <= q everywhere, i.e. p == q (both sum to
        # 1) — the accept test above then always fires (u*q <= p), so
        # this branch is unreachable with total == 0; guard anyway.
        dist = residual / total if total > 0 else p
        tok = int(jax.random.categorical(
            jax.random.fold_in(pos_key, RESIDUAL_FOLD),
            jnp.log(jnp.asarray(dist))))
        out.append(tok)
        return out
    # Every proposal accepted: the bonus draw IS the non-speculative
    # sampler at its position (same key, same spelling).
    out.append(int(_sample_one(tl[w - 1], jnp.float32(temperature),
                               jnp.int32(top_k), jnp.uint32(seed),
                               jnp.uint32(position0 + w - 1))))
    return out
