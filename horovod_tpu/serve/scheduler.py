"""Request lifecycle + the SLO-knobbed scheduler.

The scheduler is pure host bookkeeping between compiled steps — it
never touches device arrays. That host-side purity is also what makes
the TP-sharded engine's control plane trivially REPLICATED: under
``ServeConfig.mesh`` the step program runs SPMD with head-sharded
pages, but admission, page tables, eviction picks and the prefix
index still happen exactly once here, so every chip executes the step
with identical tables by construction — no cross-chip agreement
protocol exists because there is nothing to disagree about. It owns
three decisions per step, each behind one
:class:`~horovod_tpu.serve.config.ServeConfig` knob:

* **queue order** (``policy``): ``fcfs`` arrival order, or ``sjf``
  shortest-prompt-first (minimizes mean TTFT under backlog at the cost
  of long-prompt starvation — the classic SJF trade);
* **prefill gate** (``slo``): when a NEW prefill may start.
  ``latency`` starts one whenever the lane is idle and a request is
  waiting (best TTFT — the chunk steals step time from decode);
  ``throughput`` only once a decode slot is free to take the finished
  request (decode slots never share the step with a prefill whose
  output would just wait); ``balanced`` relaxes to "a slot is free OR
  a backlog is building";
* **admission** (``admission``): ``reserve`` grants a request its
  worst-case pages up front — admitted implies it can always finish —
  while ``lazy`` grants pages as positions cross page boundaries and
  evicts (newest-admitted-first) on exhaustion.

Lifecycle (:class:`RequestState`)::

    QUEUED -> PREFILL -> DECODE -> FINISHED
        \\-> REJECTED      \\-> EVICTED (-> QUEUED again when
                                         ``requeue_evicted``)

A request that is evicted and requeued carries its generated tokens as
prompt extension (vLLM's recompute path); greedy decoding makes the
recomputation bit-identical, and the position-folded sampling keys
(:mod:`~horovod_tpu.serve.sampling`) make even temperature>0 requests
resume their exact token stream.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional, Sequence

import numpy as np

from horovod_tpu.serve.config import ServeConfig
from horovod_tpu.serve.kvcache import OutOfPages, PagedKVCache


class RequestState:
    """Lifecycle states (plain str constants — they stamp into JSON)."""

    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"
    EVICTED = "evicted"
    REJECTED = "rejected"
    #: Deadline exceeded: finished early with whatever was generated,
    #: pages freed. Terminal, like FINISHED — the client already gave
    #: up on the stream; holding its pages would starve live requests.
    TIMEOUT = "timeout"


_rid_counter = itertools.count()


@dataclasses.dataclass(eq=False)   # identity semantics: requests are
class Request:                     # tracked by `is` in slot lists
    """One in-flight generation request + its measurement trail.

    ``prompt`` is the CURRENT prompt (original prompt plus any
    pre-eviction generated tokens on a requeue); ``output`` accumulates
    every generated token across evictions, so callers always read the
    full generation off ``output`` regardless of recompute history."""

    prompt: np.ndarray                   # int32 [Lp]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    eos_token: Optional[int] = None
    seed: int = 0
    arrival: float = 0.0
    #: Deadline in seconds from arrival (None = none). The engine
    #: times the request out — ``timeout`` status, pages freed — at
    #: the first step past ``arrival + ttl``.
    ttl: Optional[float] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_rid_counter))

    #: why a REJECTED request was rejected: ``"infeasible"`` (can never
    #: run on this geometry — retrying is pointless) or ``"overloaded"``
    #: (the bounded queue/fleet is full — retry after ``retry_after``).
    reject_reason: Optional[str] = None
    #: advisory seconds-until-retry for overloaded rejections (the
    #: fleet router's load-shedding hint; None = no estimate).
    retry_after: Optional[float] = None
    #: times this request was drained off a dead replica and
    #: redispatched to a survivor (fleet bookkeeping; eviction-recompute
    #: within one engine counts in ``evictions``).
    redispatches: int = 0
    #: fleet placement: the replica currently (or last) serving this
    #: request, stamped at every dispatch (None = single engine or
    #: never dispatched). The ``--ab-prefix`` bench reads it to pin
    #: "one cold prefill per unique prefix per REPLICA".
    replica: Optional[int] = None
    #: params version this request's ENTIRE decode is pinned to (fleet
    #: bookkeeping, stamped at first dispatch). A redispatch rebases
    #: only onto a same-version replica; when that version can never
    #: be served again, :func:`restart_from_scratch` re-pins — a
    #: version mix mid-stream is impossible by construction.
    version: Optional[int] = None
    #: times this request restarted from its original prompt under a
    #: newer params version (the explicit cross-version policy).
    version_restarts: int = 0
    #: prompt tokens skipped via prefix-cache hits, cumulative across
    #: re-admissions (eviction-requeue AND dead-replica redispatch both
    #: re-match on the next replica — the redispatch-meets-prefix
    #: accounting reads this to shrink ``tokens_recomputed``).
    prefix_hit_tokens: int = 0
    #: shared pages mapped via prefix-cache hits (same cumulation).
    prefix_hit_pages: int = 0
    #: ``prefix_hit_tokens`` snapshot taken when a dead replica's
    #: drain requeued this request (None = never drained). Hits gained
    #: PAST the snapshot happened on the survivor — the portion of the
    #: pessimistic drain-time ``tokens_recomputed`` that was never
    #: actually recomputed.
    prefix_hits_at_drain: Optional[int] = None

    #: disaggregated serving: this admission runs PREFILL ONLY — the
    #: engine parks the request in its handoff bay at prefill
    #: completion (first token emitted) instead of decoding it, and the
    #: fleet ships the KV pages to a decode-pool replica. Stamped per
    #: DISPATCH by the fleet (a redispatch to a colocated fleet or a
    #: fresh prefill replica re-stamps it), False everywhere else.
    prefill_only: bool = False

    state: str = RequestState.QUEUED
    #: prompt tokens already prefilled (chunk progress).
    prefill_pos: int = 0
    #: tokens generated since the last (re)admission.
    generated: List[int] = dataclasses.field(default_factory=list)
    #: all tokens generated across evictions — the user-visible output.
    output: List[int] = dataclasses.field(default_factory=list)
    #: logical->physical page table, length cache.pages_per_seq,
    #: 0 (the null page) = unmapped.
    page_table: Optional[np.ndarray] = None
    #: physical pages held (the allocator's grant).
    pages: List[int] = dataclasses.field(default_factory=list)
    evictions: int = 0
    #: set by Scheduler.requeue — keeps the head-of-queue priority of
    #: an evicted request visible to the sjf sort.
    requeued: bool = False
    #: original request sizes (requeues mutate prompt/max_new_tokens).
    orig_prompt_len: int = 0
    orig_max_new: int = 0

    # -- measurement trail (clock() stamps, engine-filled) ------------
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    token_times: List[float] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.ttl is not None and self.ttl <= 0:
            raise ValueError(f"ttl must be > 0 seconds (or None), got "
                             f"{self.ttl}")
        if not self.orig_prompt_len:
            self.orig_prompt_len = int(self.prompt.size)
        if not self.orig_max_new:
            self.orig_max_new = int(self.max_new_tokens)

    # ------------------------------------------------------ positions

    @property
    def deadline(self) -> Optional[float]:
        """Absolute clock time past which the request times out."""
        return None if self.ttl is None else self.arrival + self.ttl

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def next_pos(self) -> int:
        """Absolute cache position the next decode step writes (the
        position of the token being fed back)."""
        return self.prompt_len + len(self.generated) - 1

    @property
    def sample_index(self) -> int:
        """0-based index (within the FULL generation) of the token the
        next sample produces — the sampling key's fold position, stable
        across evictions/recomputes."""
        return self.orig_prompt_len + len(self.output)

    @property
    def done_generating(self) -> bool:
        return len(self.generated) >= self.max_new_tokens

    def spec_window(self, k: int) -> int:
        """Budget clamp for a speculative tick: how many draft
        proposals this request can still USE. A tick emits between 1
        and proposals+1 tokens, so proposals beyond
        ``max_new_tokens - len(generated) - 1`` could only produce
        tokens past the budget (the host would drop them) while
        writing KV rows past the request's reserve-mode page grant —
        clamp instead. 0 = degenerate tick (verify-only, exactly one
        token, the plain decode step in a width-1 window)."""
        return max(0, min(k, self.max_new_tokens
                          - len(self.generated) - 1))

    def hit_eos(self, default_eos: Optional[int]) -> bool:
        eos = self.eos_token if self.eos_token is not None else default_eos
        return bool(self.generated) and eos is not None \
            and self.generated[-1] == eos


class Scheduler:
    """Queue + admission + the prefill gate over one
    :class:`~horovod_tpu.serve.kvcache.PagedKVCache`."""

    def __init__(self, cache: PagedKVCache, config: ServeConfig,
                 prefix=None):
        self.cache = cache
        self.config = config
        #: Optional :class:`~horovod_tpu.serve.prefix.PrefixIndex` —
        #: when set, admission maps a prompt's matched pages read-only
        #: (retain) and counts/allocates only the MISSED pages.
        self.prefix = prefix
        self.queue: List[Request] = []
        self.rejected: List[Request] = []

    # ------------------------------------------------------ submission

    def submit(self, req: Request) -> bool:
        """Queue a request; False = hard-rejected (can never run, or
        the bounded queue is full). Rejection is terminal; the request
        carries ``reject_reason`` so clients can tell "never retry"
        (infeasible) from "retry later" (overloaded)."""
        c = self.config
        if not self.cache.fits(req.prompt_len, req.max_new_tokens):
            req.state = RequestState.REJECTED
            req.reject_reason = "infeasible"
            self.rejected.append(req)
            return False
        if c.max_queue and len(self.queue) >= c.max_queue:
            req.state = RequestState.REJECTED
            req.reject_reason = "overloaded"
            self.rejected.append(req)
            return False
        req.state = RequestState.QUEUED
        self.queue.append(req)
        return True

    def requeue(self, req: Request) -> bool:
        """Re-admit an evicted request: its generated tokens extend the
        prompt (recompute path) and its budget shrinks accordingly."""
        if not rebase_for_recompute(req):
            # Nothing left to generate — it was evicted on its last
            # token; treat as finished (engine stamps the clock).
            req.state = RequestState.FINISHED
            return False
        # Head of the queue, not the tail: an evicted request already
        # consumed service and holds its requester's latency budget.
        req.state = RequestState.QUEUED
        req.requeued = True
        self.queue.insert(0, req)
        return True

    # ------------------------------------------------------- ordering

    def _order(self):
        if self.config.policy == "sjf":
            # Stable sort: equal keys keep arrival order. Evicted
            # requeues rank FIRST regardless of prompt length —
            # their prompt grew by the generated prefix, so a plain
            # length sort would push them behind every shorter new
            # arrival and starve them out of the head-of-queue
            # priority requeue() granted.
            self.queue.sort(
                key=lambda r: (0 if r.requeued else 1, r.prompt_len))

    def queued(self) -> int:
        return len(self.queue)

    # --------------------------------------------------------- gating

    def prefill_gate(self, free_slots: int) -> bool:
        """May a NEW prefill start this step? (The SLO knob; the lane
        being idle and the in-flight limit are the caller's checks.)"""
        slo = self.config.slo
        if slo == "latency":
            return True
        if slo == "throughput":
            return free_slots > 0
        return free_slots > 0 or len(self.queue) >= 2   # balanced

    def pick_prefill(self, free_slots: int, in_flight: int) -> \
            Optional[Request]:
        """Pop the next request to start prefilling, or None. Applies
        the in-flight limit, the SLO gate, queue policy, and admission
        control (reserve mode: the worst case must be allocatable NOW —
        the queue head WAITS rather than being skipped, preserving the
        policy order; lazy mode: one page is enough to start)."""
        if not self.queue or in_flight >= self.config.in_flight_limit \
                or not self.prefill_gate(free_slots):
            return None
        self._order()
        req = self.queue[0]
        if not self._admit(req):
            return None
        self.queue.pop(0)
        req.state = RequestState.PREFILL
        return req

    # ------------------------------------------------------ admission

    def _admit(self, req: Request) -> bool:
        c = self.config
        if req.page_table is None:
            req.page_table = np.zeros(self.cache.pages_per_seq, np.int32)
        # Prefix-cache probe: the longest chain of already-filled pages
        # for this prompt. Pure lookup — pages are retained only once
        # the admission is known to stick (the waiting queue head
        # re-probes every step; a failed try must not leak holders).
        hit, matched = [], 0
        if self.prefix is not None:
            hit, matched = self.prefix.match(req.prompt)
        alloc = self.cache.allocator
        if c.admission == "reserve":
            need = self.cache.pages_needed(req.prompt_len,
                                           req.max_new_tokens)
            if need - len(hit) > alloc.available and \
                    self.prefix is not None:
                # Index-only holds are the lowest-priority pages:
                # reclaim cold leaves before making the head wait —
                # then RE-match, since a reclaimed leaf could have
                # been part of this very chain.
                self.prefix.reclaim(need - len(hit) - alloc.available)
                hit, matched = self.prefix.match(req.prompt)
            if need - len(hit) > alloc.available:
                return False
            grant = alloc.alloc(need - len(hit))
        else:
            # lazy: map the hits plus the FIRST missed page only; grow
            # via ensure_pages.
            if alloc.available < 1 and self.prefix is not None:
                self.prefix.reclaim(1)
                hit, matched = self.prefix.match(req.prompt)
            if alloc.available < 1:
                return False
            grant = alloc.alloc(1)
        if hit:
            alloc.retain(hit)
            req.pages.extend(hit)
            req.page_table[:len(hit)] = np.asarray(hit, np.int32)
            req.prefill_pos = matched
            req.prefix_hit_tokens += matched
            req.prefix_hit_pages += len(hit)
        req.pages.extend(grant)
        req.page_table[len(hit):len(hit) + len(grant)] = \
            np.asarray(grant, np.int32)
        if self.prefix is not None:
            self.prefix.note_admission(len(hit), matched)
        return True

    def ensure_pages(self, req: Request, last_pos: int,
                     evict: Callable[[Request], bool]) -> bool:
        """Lazy-mode growth: map every page slot up to ``last_pos``.
        On exhaustion, calls ``evict(requester)`` (the engine frees a
        victim's pages) until satisfied or evict() gives up. Returns
        False when the REQUESTER itself must be evicted (evict() chose
        it / nothing else to evict). Reserve mode: no-op by
        construction (the table was fully granted at admission)."""
        need_slot = last_pos // self.cache.config.page_size
        for slot in range(need_slot + 1):
            if req.page_table[slot] != 0:
                continue
            while True:
                try:
                    req.page_table[slot] = page = \
                        self.cache.allocator.alloc(1)[0]
                    req.pages.append(page)
                    break
                except OutOfPages:
                    if not evict(req):
                        return False
        return True

    # -------------------------------------------------------- release

    def release(self, req: Request) -> None:
        """Drop the request's hold on every page it maps (finish OR
        evict) — through the REFCOUNTED path, so a page the prefix
        index (or another request) still holds stays alive and only
        exclusively-held pages return to the free list (HVD013: the
        strict ``free()`` is kvcache-internal)."""
        if req.pages:
            self.cache.allocator.release(req.pages)
            req.pages = []
        if req.page_table is not None:
            req.page_table[:] = 0

    def drop(self, req: Request) -> None:
        """Remove a request from the queue (deadline timeout while
        waiting). Queue membership is this module's invariant — callers
        must not rebuild ``queue`` themselves."""
        self.queue = [r for r in self.queue if r is not req]


def make_request(config, clock, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_token=None, seed: int = 0, arrival=None,
                 ttl=None) -> Request:
    """Build one :class:`Request` with the config/clock defaulting both
    submit surfaces share (``ServeEngine.submit`` and
    ``ServeFleet.submit``): ``eos_token`` falls back to the config's,
    ``arrival`` to now, ``ttl`` to ``config.default_ttl``. One helper
    so a future per-request knob or default change cannot silently
    apply to one surface and not the other."""
    return Request(
        prompt=prompt, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k,
        eos_token=eos_token if eos_token is not None
        else config.eos_token,
        seed=seed,
        arrival=arrival if arrival is not None else clock(),
        ttl=ttl if ttl is not None else config.default_ttl)


def rebase_for_recompute(req: Request) -> bool:
    """Fold the generated-so-far tokens into the prompt — the
    recompute arithmetic shared by eviction-requeue (within one engine)
    and dead-replica redispatch (the fleet router): the prompt grows by
    the generated prefix, the generation budget shrinks by it, and
    prefill restarts from 0. ``output`` is untouched — tokens already
    emitted are NEVER re-emitted (the at-most-once guarantee) — and
    ``sample_index`` stays position-stable, so greedy recompute is
    bit-identical and temperature>0 requests re-draw their exact
    stream. Returns False when nothing is left to generate (the
    request died on its very last token; the caller finishes it)."""
    if req.generated:
        req.prompt = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)])
        req.max_new_tokens -= len(req.generated)
        req.generated = []
    req.prefill_pos = 0
    return req.max_new_tokens >= 1


def restart_from_scratch(req: Request) -> None:
    """The explicit cross-version redispatch policy's arithmetic: a
    request pinned to a params version no replica can ever serve again
    RESTARTS — original prompt, full budget, stream and measurement
    trail reset — so its whole decode re-pins to one (newer) version.
    The inverse trade of :func:`rebase_for_recompute`: the rebase keeps
    emitted tokens at the cost of requiring same-version weights; the
    restart discards them (the router signals the client a stream
    restart) because continuing a half-stream under different weights
    would silently emit a token sequence NO single model ever
    produced."""
    req.prompt = req.prompt[:req.orig_prompt_len]
    req.max_new_tokens = req.orig_max_new
    req.generated = []
    req.output = []
    req.prefill_pos = 0
    req.version = None
    req.version_restarts += 1
    req.t_first_token = None
    req.token_times = []


def pick_victim(candidates: Sequence[Request],
                requester: Request) -> Optional[Request]:
    """Lazy-mode eviction policy: newest-admitted-first (LIFO over
    ``t_admit``), never the requester if any other candidate exists —
    the oldest requests are closest to finishing and have consumed the
    most recompute-able service, so evicting the newest minimizes
    wasted work. Returns None when the requester is the only
    candidate (the engine then evicts the requester itself)."""
    others = [r for r in candidates if r is not requester]
    if not others:
        return None
    return max(others, key=lambda r: (r.t_admit or 0.0, r.rid))
