"""Continuous-batching LM inference serving (`horovod_tpu.serve`).

The serving half of the inference story (the decode lane,
`tools/decode_bench.py` / `models.parallel_lm.lm_decode`, is the
single-batch baseline): an Orca-style iteration-level batching engine
over a vLLM-style paged KV cache, TPU-native — every step executes ONE
compiled program of fixed shape (a fixed count of decode slots plus one
chunked-prefill lane), so requests join and leave the batch between
steps without ever recompiling.

* :mod:`~horovod_tpu.serve.kvcache` — block/paged KV cache: fixed-size
  pages, a free-list allocator, per-request page tables, admission
  control that rejects/queues when pages run out;
* :mod:`~horovod_tpu.serve.engine` — the continuous-batching step loop
  (mixed prefill+decode program, in-flight join/leave, greedy +
  temperature/top-k sampling, token-exact with ``lm_decode`` when
  greedy);
* :mod:`~horovod_tpu.serve.scheduler` — request lifecycle
  (queued → prefill → decode → finished/evicted) and the SLO-knobbed
  scheduler (FCFS vs shortest-prompt-first, latency-vs-throughput);
* :mod:`~horovod_tpu.serve.prefix` — copy-on-write prefix caching
  (``ServeConfig.prefix_caching``): a radix-tree index over
  page-aligned token chunks maps a prompt to the longest chain of
  already-filled pages; admission maps them read-only into the new
  request's table (refcounted sharing in the allocator — retain/
  release; shared pages never re-enter the free list, never become
  eviction victims), prefill starts at the first miss, any write to a
  shared page copies-on-write first, and the fleet router rendezvous-
  hashes the normalized prefix so prefix-mates land on the replica
  already holding the pages — one cold prefill per unique prefix per
  replica, hit streams bit-identical to the cold path;
* :mod:`~horovod_tpu.serve.sampling` — vectorized per-slot sampling,
  plus the speculative-decoding surfaces
  (``ServeConfig(speculate_k=K)``): the in-step draft proposal draw
  and the host-side acceptance rule
  (:func:`~horovod_tpu.serve.sampling.speculative_accept` — longest
  agreeing prefix under greedy, provably bit-identical to
  ``lm_decode``; Leviathan rejection sampling under position-folded
  domain-separated keys otherwise). The draft is the target's first
  ``draft_layers`` layers sharing embed/head AND the target's own KV
  pages (``models.parallel_lm.draft_params``) — no second cache, no
  extra wire traffic; the target verifies all K+1 positions in one
  rectangular-causal pass (``engine.serve_step_spec``);
* :mod:`~horovod_tpu.serve.metrics` — TTFT / per-token latency /
  page-occupancy accounting for the bench lane
  (`tools/serve_bench.py`);
* :mod:`~horovod_tpu.serve.fleet` + :mod:`~horovod_tpu.serve.router` —
  the fault-tolerant multi-replica fleet: N engines behind a
  least-loaded router with classified replica incidents (PR 9's
  heartbeat watchdog + exit taxonomy), drain/redispatch of a dead
  replica's in-flight requests (at-most-once, greedy bit-identical),
  budgeted exponential-backoff relaunches, and bounded-queue load
  shedding ("rejected: overloaded" + retry-after);
* :mod:`~horovod_tpu.serve.transport` +
  :mod:`~horovod_tpu.serve.worker` — the cross-process fleet lane
  (``FleetConfig.transport="process"``): each replica its own worker
  process behind a length-prefixed, checksummed, deadline-checked
  frame protocol over a Unix socket — real crash isolation, with
  every transport failure converted into the fleet's replica-death
  path (typed :class:`~horovod_tpu.serve.transport.TransportError`
  taxonomy, never an RPC-level retry);
* the same frame protocol over TCP (``transport="tcp"``) places
  workers across HOSTS (``FleetConfig.hosts``, ssh placement, a
  shared-secret connect handshake): a lost machine is one classified
  ``host_down`` incident with every replica drained + redispatched,
  stall liveness rides a heartbeat sequence in the RPC replies, and
  :mod:`~horovod_tpu.serve.netfault` injects partitions/delays/
  trickles/torn frames deterministically on loopback TCP for CI;
* :mod:`~horovod_tpu.serve.params_wire` — wire-native versioned
  weight distribution: weights are a content-addressed artifact
  (deterministic blob + sha256) chunk-streamed to every worker
  incarnation over the frame protocol (per-chunk CRC,
  assemble-to-temp, digest-verify, atomic rename,
  resume-from-offset after torn transfers) — no shared-filesystem
  assumption on any transport — and ``ServeFleet.update_params``
  rolls new weights through the fleet with zero downtime, each
  request's decode pinned to exactly one params version.

Architecture, page math, and the SLO tuning runbook: docs/serving.md.
"""

from horovod_tpu.serve.config import FleetConfig, ServeConfig
from horovod_tpu.serve.engine import ServeEngine
from horovod_tpu.serve.fleet import (ProcessReplica, Replica, ServeFleet,
                                     TcpReplica)
from horovod_tpu.serve.netfault import FaultableSocket, NetFaults
from horovod_tpu.serve.kvcache import OutOfPages, PageAllocator, PagedKVCache
from horovod_tpu.serve.prefix import (PrefixIndex, aligned_prefix_len,
                                      prefix_route_key, rendezvous_rank)
from horovod_tpu.serve.scheduler import Request, RequestState, Scheduler
from horovod_tpu.serve.transport import (ChecksumError, ConnectionLost,
                                         DeadlineExceeded, FrameError,
                                         RemoteCallError, TransportError)

__all__ = [
    "ChecksumError",
    "ConnectionLost",
    "DeadlineExceeded",
    "FaultableSocket",
    "FleetConfig",
    "FrameError",
    "NetFaults",
    "OutOfPages",
    "PageAllocator",
    "PagedKVCache",
    "PrefixIndex",
    "ProcessReplica",
    "RemoteCallError",
    "Replica",
    "Request",
    "RequestState",
    "Scheduler",
    "ServeConfig",
    "ServeEngine",
    "ServeFleet",
    "TcpReplica",
    "TransportError",
    "aligned_prefix_len",
    "prefix_route_key",
    "rendezvous_rank",
]
