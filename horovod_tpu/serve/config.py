"""Serving engine configuration: page math + scheduler SLO knobs.

One frozen dataclass so every layer (cache, scheduler, engine, bench)
reads the same validated numbers. The page math contract:

* the model's position table length ``Lmax`` must divide into
  ``page_size`` pages — each request's logical cache is ``Lmax //
  page_size`` page slots, mapped to physical pages by its page table;
* physical page 0 is RESERVED as the null sink: inactive lanes and
  padded prefill rows scatter their K/V there, and short page tables
  pad with it (reads beyond a request's length are masked, so its
  garbage is never observed) — ``num_pages - 1`` pages are allocatable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

#: Scheduler admission policies (docs/serving.md "Scheduler knobs").
POLICIES = ("fcfs", "sjf")
#: The latency-vs-throughput SLO knob positions.
SLO_MODES = ("latency", "balanced", "throughput")
#: Page-allocation disciplines.
ADMISSIONS = ("reserve", "lazy")
#: Decode-attention implementations: ``gather`` reconstructs the dense
#: ``[S, Lmax, H, D]`` logical cache per layer per step (the exactness
#: reference); ``paged`` streams only each slot's live pages through
#: the fused Pallas kernel (:mod:`horovod_tpu.ops.paged_attention`).
ATTENTIONS = ("gather", "paged")
#: Fleet replica placements: ``inproc`` runs every engine in the
#: router's process (the CI fast lane, zero transport overhead, NO
#: crash isolation); ``process`` runs each replica as its own worker
#: process (:mod:`horovod_tpu.serve.worker`) behind the deadline-
#: checked framed RPC transport (:mod:`horovod_tpu.serve.transport`)
#: — a replica crash is one SIGKILLed OS process, never the router;
#: ``tcp`` runs the same frame protocol over TCP with a shared-secret
#: connect handshake, placing workers across HOSTS
#: (``FleetConfig.hosts``, ssh placement) so a whole machine is a
#: first-class failure domain (``host_down``).
TRANSPORTS = ("inproc", "process", "tcp")

#: Host names a TCP worker can be spawned on WITHOUT ssh (and whose
#: workers may get router-probed free ports instead of an explicit
#: base port).
LOCAL_HOSTS = ("localhost", "127.0.0.1")


def parse_host_entry(entry) -> tuple:
    """One ``FleetConfig.hosts`` entry — ``"host"`` or ``"host:port"``
    — parsed to ``(host, port_or_None)``, validated fail-fast (the
    construction-time contract: a malformed placement must never
    survive to the first spawn). ``port`` is the BASE port for that
    host's workers (worker ``i``-th on the host binds ``port + i``);
    local hosts may omit it (the router probes free ports), remote
    hosts must not (the router cannot probe a port over ssh)."""
    if not isinstance(entry, str) or not entry.strip():
        raise ValueError(
            f"hosts entry {entry!r}: expected a 'host[:port]' string")
    e = entry.strip()
    if "/" in e:
        raise ValueError(
            f"hosts entry {entry!r} looks like a unix-socket path — "
            "transport='tcp' places workers at 'host[:port]' network "
            "endpoints (the unix-socket lane is transport='process')")
    host, sep, port_s = e.rpartition(":")
    if not sep:
        return e, None
    if not host:
        raise ValueError(
            f"hosts entry {entry!r}: missing the host part")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"hosts entry {entry!r}: port {port_s!r} is not an "
            "integer") from None
    if not 1 <= port <= 65535:
        raise ValueError(
            f"hosts entry {entry!r}: port {port} outside 1..65535")
    return host, port


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs for :class:`horovod_tpu.serve.ServeEngine`.

    ``page_size``/``num_pages`` size the paged KV cache (page 0
    reserved). ``decode_slots`` fixes the compiled program's decode
    batch; ``prefill_chunk`` the tokens per step the prefill lane
    processes (the chunked-prefill knob: bigger chunks reach the first
    token faster, smaller chunks steal less of the step from decode).

    ``policy`` picks the queue order (``fcfs`` arrival order /
    ``sjf`` shortest-prompt-first). ``slo`` is the latency-vs-
    throughput knob gating when NEW prefills start (see
    :meth:`Scheduler.prefill_gate <horovod_tpu.serve.scheduler.
    Scheduler>`): ``latency`` starts a prefill whenever the lane is
    idle, ``throughput`` only once a decode slot is free to take the
    finished request, ``balanced`` in between.

    ``admission`` picks the page discipline: ``reserve`` allocates a
    request's worst-case pages up front (admission control — a request
    only starts when it can always finish; the default), ``lazy``
    allocates pages as positions cross page boundaries and EVICTS on
    exhaustion (higher occupancy, eviction-recompute risk).

    ``attention`` picks the decode-attention path: ``gather`` (the
    default and the exactness reference) reconstructs each slot's
    dense ``[Lmax, H, D]`` cache per layer per step — O(Lmax) HBM
    traffic regardless of position — while ``paged`` streams only the
    ``ceil((t+1)/page_size)`` live pages through the fused Pallas
    kernel (:func:`horovod_tpu.ops.paged_attention.
    paged_attention_decode`; docs/serving.md "The paged-attention
    decode kernel"). Greedy token streams are bit-identical either
    way; the prefill lane keeps the full gather in both modes.

    ``mesh`` shards the engine's compiled step SPMD over a
    :class:`~horovod_tpu.parallel.logical.LogicalMesh` built from the
    PR-17 config string (e.g. ``"dp=1,tp=4"``): attention heads, MLP
    features and the vocab projection shard Megatron-style over the
    tensor axis, and the per-layer KV page arrays become
    ``[num_pages, page_size, H/tp, D]`` per chip — per-chip KV and
    weight bytes drop by 1/tp while page tables, the free-list
    allocator and the prefix index stay replicated host-side
    (docs/serving.md "TP-sharded decode"). Only the tensor role axis
    may exceed size 1 (data parallelism belongs to the FLEET — one
    engine is one logical replica); the string's syntax and axis
    shape are validated HERE at construction, the model-dependent
    divisibility (H/mlp/vocab % tp) and the device budget at ENGINE
    construction — never at first compile. ``None`` (default) is the
    unsharded single-chip engine, the exactness reference the tp path
    is pinned bit-identical to.

    ``speculate_k`` > 0 turns on speculative decoding: each engine tick
    the layer-skip draft (the target's first ``draft_layers`` layers
    sharing embed/head) proposes up to ``k`` tokens per slot and the
    target verifies all ``k+1`` positions in one rectangular-causal
    pass — up to ``k+1`` tokens emitted per slot per tick, greedy
    streams pinned bit-identical to the non-speculative engine (the
    acceptance rule keeps only target argmaxes). See docs/serving.md
    "Speculative decoding".

    ``prefix_caching`` turns on the copy-on-write prefix cache
    (:mod:`horovod_tpu.serve.prefix`; docs/serving.md "Prefix
    caching"): admission maps a prompt's longest chain of
    already-filled pages into the request's table read-only
    (refcounted sharing — ``kvcache.PageAllocator.retain``), prefill
    starts at the first miss, and the admission math counts only the
    MISSED pages. Off by default: the cold path is the exactness
    reference, and hit streams are pinned bit-identical to it.
    """

    page_size: int = 16
    num_pages: int = 64
    decode_slots: int = 4
    prefill_chunk: int = 32
    max_in_flight: int = 0      # 0 = decode_slots + the prefill lane
    policy: str = "fcfs"
    slo: str = "balanced"
    admission: str = "reserve"
    attention: str = "gather"
    #: Copy-on-write prefix caching (serve/prefix.py). Off = seed
    #: behavior: every request pays a full cold prefill.
    prefix_caching: bool = False
    #: Speculative decoding (docs/serving.md "Speculative decoding"):
    #: the layer-skip draft proposes up to ``speculate_k`` tokens per
    #: slot per tick and the target verifies all ``k+1`` positions in
    #: ONE rectangular-causal pass. 0 (default) = off — the
    #: single-token decode lane, the exactness reference the spec path
    #: is pinned bit-identical to under greedy acceptance.
    speculate_k: int = 0
    #: Draft depth for speculation: the draft model is the target's
    #: FIRST ``draft_layers`` transformer layers sharing embed/head
    #: (:func:`models.parallel_lm.draft_params` — self-speculative, no
    #: second weight artifact to distribute). 0 = auto: half the
    #: target's depth, at least 1. Model-dependent validation (1 <=
    #: draft_layers <= num_layers) happens at ENGINE construction,
    #: like the tp divisibility checks.
    draft_layers: int = 0
    eos_token: Optional[int] = None
    max_queue: int = 0          # 0 = unbounded
    requeue_evicted: bool = True
    #: LogicalMesh config string ("dp=1,tp=4") sharding the compiled
    #: step; None = unsharded single-chip engine (the reference).
    mesh: Optional[str] = None
    #: Default per-request deadline in seconds from arrival (None =
    #: no deadline; a per-request ``ttl=`` overrides). A request still
    #: unfinished past its deadline is finished with the ``timeout``
    #: status and its pages freed at the next engine step — one wedged
    #: or abandoned stream can never hold KV pages forever.
    default_ttl: Optional[float] = None

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"sink), got {self.num_pages}")
        if self.decode_slots < 1:
            raise ValueError(
                f"decode_slots must be >= 1, got {self.decode_slots}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        if self.policy not in POLICIES:
            raise ValueError(f"policy {self.policy!r} not in {POLICIES}")
        if self.slo not in SLO_MODES:
            raise ValueError(f"slo {self.slo!r} not in {SLO_MODES}")
        if self.admission not in ADMISSIONS:
            raise ValueError(
                f"admission {self.admission!r} not in {ADMISSIONS}")
        if self.attention not in ATTENTIONS:
            raise ValueError(
                f"attention {self.attention!r} not in {ATTENTIONS}")
        if self.speculate_k < 0:
            raise ValueError(
                f"speculate_k must be >= 0 (0 = speculation off), got "
                f"{self.speculate_k}")
        if self.draft_layers < 0:
            raise ValueError(
                f"draft_layers must be >= 0 (0 = auto: half the "
                f"target's depth), got {self.draft_layers}")
        if self.draft_layers > 0 and self.speculate_k == 0:
            raise ValueError(
                f"draft_layers={self.draft_layers} without "
                "speculate_k — the draft only exists to propose "
                "speculative tokens (set speculate_k >= 1)")
        if self.default_ttl is not None and self.default_ttl <= 0:
            raise ValueError(
                f"default_ttl must be > 0 seconds (or None), got "
                f"{self.default_ttl}")
        if self.mesh is not None:
            self.mesh_axes()   # fail-fast: syntax + axis-shape errors

    def mesh_axes(self) -> Optional[dict]:
        """The parsed ``mesh`` axes (``None`` when unsharded),
        validated for the serve shape: canonical PR-17 syntax, fully
        specified sizes (no ``-1`` wildcard — the engine must know its
        device budget before it touches one), and only the TENSOR role
        axis above size 1. Raises
        :class:`~horovod_tpu.common.exceptions.InvalidArgumentError`
        at ServeConfig construction, never at first compile."""
        if self.mesh is None:
            return None
        from horovod_tpu.common.exceptions import InvalidArgumentError
        from horovod_tpu.parallel.logical import (
            ROLE_AXES,
            parse_mesh_config,
        )

        axes = parse_mesh_config(self.mesh)    # raises on bad syntax
        tensor = ROLE_AXES["tensor"]
        for name, size in axes.items():
            if size == -1:
                raise InvalidArgumentError(
                    f"ServeConfig.mesh {self.mesh!r}: the serve mesh "
                    f"must be fully specified — '-1' wildcards resolve "
                    "against a device count the config does not know")
            if name != tensor and size != 1:
                raise InvalidArgumentError(
                    f"ServeConfig.mesh {self.mesh!r}: axis {name!r} has "
                    f"size {size}, but one engine shards over the "
                    f"tensor axis ({tensor!r}) only — data parallelism "
                    "is the FLEET's job (one engine per mesh is one "
                    "logical replica)")
        return axes

    @property
    def tp_degree(self) -> int:
        """The tensor-parallel degree the ``mesh`` string names (1 when
        unsharded)."""
        axes = self.mesh_axes()
        if not axes:
            return 1
        from horovod_tpu.parallel.logical import ROLE_AXES

        return axes.get(ROLE_AXES["tensor"], 1)

    @property
    def in_flight_limit(self) -> int:
        """Admitted-requests cap. The default matches the step
        program's lane count — ``decode_slots`` + the one prefill
        lane — so saturation never silences the ``latency`` SLO gate
        (a prefill can always start while every slot decodes)."""
        return self.max_in_flight if self.max_in_flight > 0 \
            else self.decode_slots + 1


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Knobs for :class:`horovod_tpu.serve.ServeFleet` — the
    multi-replica layer on top of one :class:`ServeConfig` (every
    replica runs the same engine geometry).

    ``max_queue`` bounds the ROUTER's admission queue — the fleet's
    load-shedding valve: past it, new requests are rejected terminally
    with ``reject_reason="overloaded"`` and a ``retry_after`` hint
    instead of queueing until their TTFT diverges. 0 = unbounded (no
    shedding).

    ``max_restarts`` is the fleet-wide replica relaunch budget (the
    elastic supervisor's discipline: a crash loop must converge, not
    burn the host); each relaunch backs off exponentially —
    ``backoff_base * 2**attempts_of_that_replica``, capped at
    ``backoff_cap``. A replica whose relaunch would exceed the budget
    is marked ``failed`` and the fleet degrades (load shedding takes
    over).

    ``watchdog_timeout`` > 0 arms the stale-heartbeat watchdog
    (:class:`horovod_tpu.elastic.supervisor.HealthWatchdog`): every
    live replica's heartbeat stamps at the END of each fleet TICK (all
    together, once every replica has stepped — per-step stamping would
    let one slow step age every peer's file into a spurious kill), so
    a replica that silently stops stepping is SIGKILL-classified
    ``stalled`` and relaunched instead of wedging its slice of the
    fleet forever. Size the timeout ABOVE a full fleet tick (the sum
    of all replicas' step times in-process — a relaunch recompile is
    one step), not one replica's step. The directory is ALWAYS
    namespaced per fleet instance (under ``heartbeat_dir`` when
    given) — two fleets, or a fleet and a training supervisor, on one
    host never watch each other's files.

    ``retry_after_min`` floors the overload hint so clients never get
    told to hammer back immediately.

    ``transport`` places the replicas: ``inproc`` (default — the fast,
    CI-exercisable lane) keeps every engine in the router's process;
    ``process`` spawns each replica as its own
    ``python -m horovod_tpu.serve.worker`` OS process behind the
    framed Unix-socket RPC transport, so a replica crash (a REAL
    ``SIGKILL``, an OOM, a segfault) takes down exactly one worker;
    ``tcp`` runs the same frame protocol over TCP (plus a
    shared-secret connect handshake — a TCP listener is
    network-reachable) and places workers across ``hosts``: each entry
    is ``"host"`` or ``"host:port"`` (``port`` = that host's base
    port; its ``i``-th worker binds ``port + i``), replicas assigned
    round-robin, remote hosts reached over ssh (the launcher's pty-HUP
    kill discipline, secret over stdin). With ``hosts=None`` every
    worker runs on loopback — the CI lane. A lost HOST is then one
    failure domain: all its replicas drain and redispatch in a single
    classified ``host_down`` incident. The transport/hosts
    combination is validated HERE, at construction (``hosts`` without
    ``transport="tcp"``, unix-socket-path entries, duplicate
    host:port pairs, portless remote hosts all raise) — never at
    first spawn.
    Every RPC then carries ``rpc_deadline`` seconds of budget — size
    it ABOVE the worker's one-off costs inside a call (the first
    ``step`` poll after a (re)spawn waits out the engine build + jax
    import behind the worker's lock) — and any transport failure is
    converted into the replica-death path, never retried.
    ``spawn_timeout`` bounds how long a (re)spawned worker may take to
    start listening; ``shutdown_deadline`` is :meth:`ServeFleet.close
    <horovod_tpu.serve.fleet.ServeFleet.close>`'s budget for the
    graceful ``shutdown`` RPC before it escalates SIGTERM → SIGKILL.

    **Weight distribution** (process/tcp transports): every worker
    incarnation receives its ServeConfig and a versioned params
    artifact OVER THE WIRE at spawn (``put_config`` + chunked
    ``push_*`` RPCs, :mod:`horovod_tpu.serve.params_wire`) — no shared
    filesystem. ``push_chunk_bytes`` bounds each transfer frame (its
    base64 form must stay under the transport's 16 MiB frame cap);
    ``push_retries`` budgets how many times one push may resume after
    a transport failure (chunk writes are idempotent and
    digest-verified, so the push lane is the ONE place a
    TransportError is retried — under the same exponential backoff as
    relaunches) before the replica takes the ordinary death path.

    **Disaggregated serving**: ``pools={"prefill": P, "decode": D}``
    (``P + D == replicas``) splits the fleet into a prefill pool
    (replica ids ``0..P-1``) and a decode pool (the rest) behind the
    same router. The prefill pool runs each request's chunked prefill
    to completion and ships the finished KV pages over the wire
    (:mod:`~horovod_tpu.serve.kv_wire`) to a decode replica picked by
    the router's ordinary load keys + prefix-affinity; the two pools
    are scheduled independently — prefill admission never consumes a
    decode slot and vice versa. ``pools=None`` (default) keeps the
    colocated layout: every replica does both phases. The mapping
    from replica id to pool is fixed for the fleet's lifetime
    (relaunches keep their role), so a death on either side drains and
    redispatches WITHIN the dead replica's pool.
    """

    replicas: int = 2
    max_queue: int = 0            # 0 = unbounded router queue
    max_restarts: int = 2
    backoff_base: float = 0.05
    backoff_cap: float = 2.0
    watchdog_timeout: float = 0.0  # 0 = watchdog disabled
    heartbeat_dir: Optional[str] = None   # base dir; namespaced per fleet
    retry_after_min: float = 0.05
    transport: str = "inproc"
    rpc_deadline: float = 60.0     # per-RPC budget (process/tcp transport)
    spawn_timeout: float = 120.0   # worker must listen within this
    shutdown_deadline: float = 2.0  # graceful-shutdown RPC budget
    #: TCP placement: host entries ("host" or "host:port"), replicas
    #: round-robin. None (with transport="tcp") = all on loopback.
    hosts: Optional[tuple] = None
    #: Params-transfer chunk size in bytes (process/tcp transports).
    push_chunk_bytes: int = 1 << 20
    #: Budgeted resume-retries per params push before replica death.
    push_retries: int = 2
    #: Disaggregated prefill/decode pools: {"prefill": P, "decode": D}
    #: with P + D == replicas (normalized to a sorted tuple of pairs so
    #: the frozen config stays hashable). None = colocated (default).
    pools: Optional[Any] = None

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(
                f"replicas must be >= 1, got {self.replicas}")
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue must be >= 0 (0 = unbounded), got "
                f"{self.max_queue}")
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.backoff_base < 0 or self.backoff_cap < self.backoff_base:
            raise ValueError(
                f"need 0 <= backoff_base <= backoff_cap, got "
                f"{self.backoff_base}/{self.backoff_cap}")
        if self.watchdog_timeout < 0:
            raise ValueError(
                f"watchdog_timeout must be >= 0 (0 disables), got "
                f"{self.watchdog_timeout}")
        if self.retry_after_min <= 0:
            raise ValueError(
                f"retry_after_min must be > 0, got "
                f"{self.retry_after_min}")
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"transport {self.transport!r} not in {TRANSPORTS}")
        if self.rpc_deadline <= 0:
            raise ValueError(
                f"rpc_deadline must be > 0 seconds (every RPC is "
                f"deadline-checked), got {self.rpc_deadline}")
        if self.spawn_timeout <= 0:
            raise ValueError(
                f"spawn_timeout must be > 0 seconds, got "
                f"{self.spawn_timeout}")
        if self.shutdown_deadline <= 0:
            raise ValueError(
                f"shutdown_deadline must be > 0 seconds, got "
                f"{self.shutdown_deadline}")
        if not 1 <= self.push_chunk_bytes <= (8 << 20):
            raise ValueError(
                f"push_chunk_bytes must be within 1..{8 << 20} (the "
                f"base64 form of a chunk must fit the 16 MiB transport "
                f"frame bound), got {self.push_chunk_bytes}")
        if self.push_retries < 0:
            raise ValueError(
                f"push_retries must be >= 0, got {self.push_retries}")
        if self.hosts is not None:
            if self.transport != "tcp":
                raise ValueError(
                    f"hosts= places workers over the network and needs "
                    f"transport='tcp' (got transport="
                    f"{self.transport!r}) — the 'process' transport is "
                    "unix-socket, same-host by construction")
            if isinstance(self.hosts, str):
                raise ValueError(
                    "hosts must be a sequence of 'host[:port]' entries, "
                    f"not the single string {self.hosts!r} (a string "
                    "would iterate per-character)")
            seen = set()
            for entry in self.hosts:
                host, port = parse_host_entry(entry)   # raises fail-fast
                if host not in LOCAL_HOSTS and port is None:
                    raise ValueError(
                        f"hosts entry {entry!r}: a remote host needs an "
                        "explicit base port — the router cannot probe "
                        "free ports over ssh")
                if (host, port) in seen:
                    raise ValueError(
                        f"duplicate host:port entry {entry!r} — two "
                        "hosts' workers would race for the same ports")
                seen.add((host, port))
            # Normalize to a tuple so the frozen config stays hashable
            # whatever sequence the caller passed.
            object.__setattr__(self, "hosts", tuple(self.hosts))
        if self.pools is not None:
            pools = dict(self.pools)
            if set(pools) != {"prefill", "decode"}:
                raise ValueError(
                    f"pools must name exactly {{'prefill', 'decode'}} "
                    f"(disaggregation is a two-phase split, not a "
                    f"general pool map), got keys {sorted(pools)}")
            for name in ("prefill", "decode"):
                n = pools[name]
                if not isinstance(n, int) or n < 1:
                    raise ValueError(
                        f"pools[{name!r}] must be an int >= 1 (an empty "
                        f"pool starves the other side), got {n!r}")
            total = pools["prefill"] + pools["decode"]
            if total != self.replicas:
                raise ValueError(
                    f"pools must partition the fleet exactly: "
                    f"prefill + decode = {total} but replicas = "
                    f"{self.replicas}")
            # Normalize to a fixed-order tuple of pairs: hashable, and
            # the prefill count is always pools[0][1].
            object.__setattr__(
                self, "pools",
                (("prefill", pools["prefill"]),
                 ("decode", pools["decode"])))

    # -- disaggregated-pool helpers (colocated fleets: pools is None) --

    @property
    def prefill_replicas(self) -> int:
        """Size of the prefill pool (0 when colocated)."""
        return 0 if self.pools is None else int(self.pools[0][1])

    def pool_of(self, replica_id: int) -> Optional[str]:
        """Pool of ``replica_id``: ids ``0..P-1`` prefill, the rest
        decode; ``None`` when the fleet is colocated. The mapping is
        positional and immutable — a relaunched replica keeps its
        role."""
        if self.pools is None:
            return None
        return "prefill" if replica_id < self.prefill_replicas \
            else "decode"
