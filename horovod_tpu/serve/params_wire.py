"""Wire-native, versioned weight distribution: the transfer codec.

PR 14's multi-host fleet closed every failure mode except the one it
documented itself: params reached remote workers through a shared
filesystem. This module removes that assumption — model weights become
a **content-addressed, versioned artifact** that streams over the
existing HVSF frame protocol in bounded chunks, and every corruption
mode a real wire (or a real crash) can produce resolves as a typed
error, never a silently wrong model:

* :func:`params_to_blob` serializes a params pytree into ONE
  deterministic byte blob (a ``HVPW`` container: JSON header with the
  tree spec + per-leaf shape/dtype, then the raw leaf bytes
  concatenated). Deliberately NOT ``np.savez``: the npz zip container
  stamps wall-clock timestamps into its entries, so two saves of
  bit-identical params produce different bytes — and a digest that is
  not content-addressed cannot anchor the fleet's
  bit-identical-weights guarantee;
* :func:`make_manifest` leads every transfer: artifact version, the
  whole-artifact sha256, total/chunk byte counts, and per-leaf specs —
  the receiver knows exactly what it must end up with before the first
  payload byte arrives;
* :func:`make_chunk` / :func:`check_chunk` frame each chunk with its
  offset and its OWN crc32 (riding inside the frame codec's payload,
  so corruption between encode and assembly — a buggy writer, a torn
  temp file — is caught even where the wire-level CRC cannot see it).
  A truncated chunk, a mis-ordered chunk, or a version mix is a typed
  :class:`~horovod_tpu.serve.transport.FrameError`; a bit flip is a
  typed :class:`~horovod_tpu.serve.transport.ChecksumError`;
* :class:`ArtifactAssembler` is the receiver's crash-safe half:
  chunks append to a temp file (contiguity enforced, so
  resume-from-offset after a torn transfer is exact by construction),
  :meth:`ArtifactAssembler.commit` digest-verifies the WHOLE artifact
  against the manifest sha256 and only then atomically renames it into
  place — a torn or corrupted transfer can never be loaded, partially
  or otherwise (the HVD012 discipline).

The framing/CRC/resume implementation itself lives in
:mod:`~horovod_tpu.serve.chunk_stream` — ONE spelling shared with the
disaggregated-serving KV handoff (:mod:`~horovod_tpu.serve.kv_wire`).
This module keeps its full pre-refactor surface (re-exported) and its
manifests/chunks stay byte-identical to their PR-15 form, pinned in
tests/test_chunk_stream.py; what remains here is the params-specific
payload (the HVPW blob codec) and the file-backed, crash-safe
assembler.

Everything except the blob <-> params converters is stdlib-only, so
the protocol-stub test worker (``python -S``, no site-packages) runs
the identical assembly/verify path the real worker does.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Dict, List, Optional, Tuple

from horovod_tpu.serve.chunk_stream import (
    DEFAULT_CHUNK_BYTES,
    check_chunk,
    check_manifest as _check_manifest,
    chunk_span as _chunk_span,
    make_chunk,
    make_manifest as _make_stream_manifest,
    sha256_hex,
)
from horovod_tpu.serve.transport import ChecksumError, FrameError

#: Blob container magic ("HoroVod Params Wire").
BLOB_MAGIC = b"HVPW"
_BLOB_HEADER = struct.Struct(">4sI")   # magic, header-JSON length

_LEAF = "__leaf_{}__"


def _np():
    import numpy as np

    return np


def _dtype(name: str):
    np = _np()
    try:
        return np.dtype(name)
    except TypeError:
        # Accelerator dtypes (bfloat16, fp8 variants) register through
        # ml_dtypes, not numpy's own namespace.
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# ----------------------------------------------------------------- blob


def params_to_blob(params) -> bytes:
    """Serialize a dict/list pytree of arrays into one DETERMINISTIC
    byte blob: identical params always produce identical bytes (and so
    one sha256) — the content-addressing every digest check and the
    fleet's bit-identical-weights pin hang off."""
    np = _np()
    leaves: List = []

    def enc(x):
        if isinstance(x, dict):
            return {k: enc(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [enc(v) for v in x]
        leaves.append(np.ascontiguousarray(np.asarray(x)))
        return _LEAF.format(len(leaves) - 1)

    spec = enc(params)
    header = json.dumps({
        "spec": spec,
        "leaves": [{"shape": list(a.shape), "dtype": a.dtype.name}
                   for a in leaves],
    }).encode("utf-8")
    parts = [_BLOB_HEADER.pack(BLOB_MAGIC, len(header)), header]
    parts.extend(a.tobytes() for a in leaves)
    return b"".join(parts)


def _blob_header(blob: bytes) -> Tuple[Dict, int]:
    """(parsed header, payload offset); typed FrameError on garbage."""
    if len(blob) < _BLOB_HEADER.size:
        raise FrameError(
            f"params blob of {len(blob)} bytes is shorter than its "
            "header — torn artifact")
    magic, hlen = _BLOB_HEADER.unpack_from(blob)
    if magic != BLOB_MAGIC:
        raise FrameError(
            f"bad params-blob magic {magic!r} — not a HVPW artifact")
    end = _BLOB_HEADER.size + hlen
    if len(blob) < end:
        raise FrameError("params blob torn inside its header")
    try:
        header = json.loads(blob[_BLOB_HEADER.size:end].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"undecodable params-blob header: {e}") from None
    return header, end


def params_from_blob(blob: bytes, as_jax: bool = True):
    """Inverse of :func:`params_to_blob`. ``as_jax`` converts leaves
    once so the engine's compiled steps don't re-upload host arrays
    every call. Torn/garbage blobs raise typed
    :class:`~horovod_tpu.serve.transport.FrameError` — this function
    is only ever fed a digest-verified artifact, so a failure here
    means the caller skipped the verify."""
    np = _np()
    header, off = _blob_header(blob)
    arrays = []
    for lf in header["leaves"]:
        dt = _dtype(lf["dtype"])
        n = int(np.prod(lf["shape"], dtype=np.int64)) * dt.itemsize \
            if lf["shape"] else dt.itemsize
        if off + n > len(blob):
            raise FrameError("params blob torn inside a leaf — short "
                             f"by {off + n - len(blob)} bytes")
        arrays.append(np.frombuffer(blob[off:off + n], dtype=dt)
                      .reshape(lf["shape"]))
        off += n
    if off != len(blob):
        raise FrameError(f"params blob carries {len(blob) - off} "
                         "trailing bytes past its last leaf")
    if as_jax:
        import jax.numpy as jnp

        arrays = [jnp.asarray(a) for a in arrays]

    def dec(x):
        if isinstance(x, dict):
            return {k: dec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [dec(v) for v in x]
        if isinstance(x, str) and x.startswith("__leaf_") \
                and x.endswith("__"):
            return arrays[int(x[7:-2])]
        return x

    return dec(header["spec"])


def blob_spec(blob: bytes) -> Dict:
    """The artifact's full structural fingerprint: the pytree spec
    (every key/nesting, leaf markers in order) plus the per-leaf
    shape/dtype list. Two artifacts with equal specs are guaranteed
    loadable into the same compiled programs — the rolling update's
    geometry gate compares THIS, not just the leaf list (a renamed key
    with identical leaf shapes is still a different model)."""
    header, _ = _blob_header(blob)
    return header


# ------------------------------------------------------------- manifest


def make_manifest(blob: bytes, *, version: int,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Dict:
    """The leading frame of every transfer: what the receiver must end
    up holding (version, whole-artifact sha256, sizes) plus the
    per-leaf specs (shape/dtype), so an operator can audit what a
    version contains without ever loading it. Shared framing under
    :func:`chunk_stream.make_manifest
    <horovod_tpu.serve.chunk_stream.make_manifest>` — the per-leaf
    specs ride as the consumer ``extra``, keeping the manifest
    byte-identical to its pre-refactor form."""
    header, _ = _blob_header(blob)
    return _make_stream_manifest(
        blob, kind="hvsf-params", version=version,
        chunk_bytes=chunk_bytes, extra={"leaves": header["leaves"]})


# ------------------------------------------------------------ assembler


class ArtifactAssembler:
    """Receiver-side assemble-to-temp + digest-verify + atomic-rename.

    One assembler per transfer attempt; the temp file is keyed on
    ``(version, sha256)`` so a NEW attempt after a torn transfer
    resumes exactly where the verified bytes end (:meth:`begin`
    returns ``have_bytes``, floored to a whole chunk — a partial
    trailing chunk from a crash mid-write is truncated away, never
    trusted). :meth:`commit` verifies the whole-artifact sha256 and
    only then renames into place; on mismatch the temp is REMOVED and
    a typed :class:`ChecksumError` raised — a torn or corrupted
    artifact is never loadable, partially or otherwise."""

    def __init__(self, directory: str):
        self.directory = directory
        self.manifest: Optional[Dict] = None
        self._have = 0

    # -------------------------------------------------------- paths

    def _paths(self) -> Tuple[str, str]:
        m = self.manifest
        stem = f"params-v{m['version']}.{m['sha256'][:12]}"
        return (os.path.join(self.directory, stem + ".part"),
                os.path.join(self.directory, stem + ".hvpw"))

    @property
    def final_path(self) -> str:
        return self._paths()[1]

    # ----------------------------------------------------- protocol

    def begin(self, manifest: Dict) -> int:
        """Arm the assembler for one transfer; returns ``have_bytes``
        — how many verified bytes of THIS (version, sha256) artifact
        already sit in the temp file, so the sender resumes from there
        instead of resending the artifact."""
        _check_manifest(manifest)
        self.manifest = dict(manifest)
        os.makedirs(self.directory, exist_ok=True)
        tmp, _ = self._paths()
        have = 0
        if os.path.exists(tmp):
            size = os.path.getsize(tmp)
            cb = int(manifest["chunk_bytes"])
            have = min((size // cb) * cb, int(manifest["total_bytes"]))
            # A partial trailing chunk (writer died mid-write) is never
            # trusted: truncate back to the last whole-chunk boundary.
            if have != size:
                with open(tmp, "r+b") as f:
                    f.truncate(have)
        else:
            with open(tmp, "wb") as f:
                f.truncate(0)
        self._have = have
        return have

    def write_chunk(self, chunk: Dict) -> int:
        """Validate + append one chunk; returns the new ``have_bytes``.
        Chunks must arrive contiguously (``offset == have``) — the
        resume contract is a single verified prefix, never a sparse
        file whose holes a digest could miss crossing."""
        if self.manifest is None:
            raise FrameError("write_chunk before begin()")
        offset, raw = check_chunk(self.manifest, chunk)
        if offset != self._have:
            raise FrameError(
                f"non-contiguous chunk: offset {offset} but only "
                f"{self._have} bytes assembled — resume must continue "
                "the verified prefix")
        tmp, _ = self._paths()
        with open(tmp, "r+b") as f:
            f.seek(offset)
            f.write(raw)
        self._have = offset + len(raw)
        return self._have

    def commit(self) -> Tuple[str, str]:
        """Digest-verify the assembled artifact and atomically rename
        it into place; returns ``(final_path, sha256)``. An incomplete
        assembly is :class:`FrameError`; a digest mismatch REMOVES the
        temp and raises :class:`ChecksumError` — there is no partial
        load, and the next attempt starts clean."""
        if self.manifest is None:
            raise FrameError("commit before begin()")
        m = self.manifest
        tmp, final = self._paths()
        if self._have != int(m["total_bytes"]):
            raise FrameError(
                f"commit of an incomplete artifact: {self._have}/"
                f"{m['total_bytes']} bytes assembled")
        digest = hashlib.sha256()
        with open(tmp, "rb") as f:
            for piece in iter(lambda: f.read(1 << 20), b""):
                digest.update(piece)
        sha = digest.hexdigest()
        if sha != m["sha256"]:
            os.unlink(tmp)
            raise ChecksumError(
                f"whole-artifact digest mismatch: assembled {sha}, "
                f"manifest says {m['sha256']} — refusing the torn/"
                "corrupted artifact (no partial load)")
        os.replace(tmp, final)   # the atomic commit (HVD012 discipline)
        return final, sha

    def abort(self) -> None:
        """Drop the in-progress temp (a transfer superseded by a newer
        version; a plain retry keeps it for the resume)."""
        if self.manifest is None:
            return
        tmp, _ = self._paths()
        try:
            os.unlink(tmp)
        except OSError:
            pass


def prune_artifacts(directory: str, keep_path: str) -> None:
    """Remove superseded committed artifacts (and stray temps) from a
    worker's artifact dir, keeping only ``keep_path`` — a long-lived
    worker rolled N times must hold one weight copy, not N (each
    artifact is a full model)."""
    keep = os.path.basename(keep_path)
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if name == keep or not name.startswith("params-v") \
                or not (name.endswith(".hvpw") or name.endswith(".part")):
            continue
        try:
            os.unlink(os.path.join(directory, name))
        except OSError:
            pass


__all__ = [
    "ArtifactAssembler", "BLOB_MAGIC", "DEFAULT_CHUNK_BYTES",
    "blob_spec", "check_chunk", "make_chunk", "make_manifest",
    "params_from_blob", "params_to_blob", "prune_artifacts",
    "sha256_hex",
]
