"""Continuous-batching serving engine: one fixed-shape compiled step.

Orca's iteration-level batching, TPU-native. Every engine step executes
ONE compiled program whose shapes never change — ``decode_slots``
single-token decode lanes plus one ``prefill_chunk``-token chunked-
prefill lane — so requests join and leave the running batch between
steps with ZERO recompilation. Two program variants compile once each
(mixed prefill+decode, and decode-only for steps with an idle prefill
lane); everything else is data:

* each decode slot attends its single query against its paged cache.
  Two selectable paths (``ServeConfig.attention``): ``gather`` (the
  default and exactness reference) reconstructs the logical cache
  ``[Lmax, H, D]`` out of the paged K/V arrays through the request's
  page-table index vector (:mod:`~horovod_tpu.serve.kvcache` — a pure
  gather, never a reshape; K and V share ONE index computation per
  lane), inserts the step's new K/V row, attends with ``q_offset = t``
  (the cache mask, exactly
  :func:`models.parallel_lm.lm_decode_step`'s spelling), and scatters
  the new row back into the pages; ``paged`` runs the same scatter
  FIRST and then streams only the slot's ``ceil((t+1)/page_size)``
  live pages through the fused Pallas kernel
  (:func:`~horovod_tpu.ops.paged_attention.paged_attention_decode`) —
  the dense intermediate never exists;
* the prefill lane runs one chunk of the current prompt through the
  RECTANGULAR-causal path — queries at global positions
  ``start..start+C-1`` over the full gathered cache with
  ``q_offset=start, k_offset=0`` (the PR-3 offset contract of
  ``ops.attention``) — writing its K/V rows through the page table;
  out-of-chunk (padded) rows scatter with ``mode="drop"`` so they
  never touch a real page.

Because both lanes reuse ``parallel_lm``'s layer functions verbatim and
masked softmax terms are exactly zero, the greedy token stream is
bit-identical to ``lm_decode`` per request (pinned in
tests/test_serve_engine.py, and CI-gated via tools/serve_bench.py
``--pin-exact``).

The page arrays are threaded through the step FUNCTIONALLY — never
donated: a live request's pages must stay readable under an in-flight
step (tools/hvdverify registers ``serve.step`` with
``forbid_donation``, the HVV104 invariant class the elastic loop
established).

**TP-sharded decode** (``ServeConfig.mesh``, e.g. ``"dp=1,tp=4"``):
the SAME step runs SPMD under ``shard_map`` over a bound
:class:`~horovod_tpu.parallel.logical.LogicalMesh` — attention heads,
MLP features and the vocab projection shard Megatron-style
(:func:`models.parallel_lm.lm_param_specs` ``vocab_parallel=True``),
the per-layer KV page arrays become ``[num_pages, page_size, H/tp,
D]`` per chip, and full-vocab f32 logits are reassembled by one tiled
all-gather (:func:`~horovod_tpu.parallel.tp.vocab_parallel_logits`)
so the host-side sampler is byte-identical to the unsharded path.
The design split: the DATA plane (K/V pages, weights) shards; the
CONTROL plane (scheduler, page tables, free-list refcounts, the radix
prefix index) stays host-side Python — one allocator makes every
decision, so "replicated across chips" holds by construction. Both
attention paths work sharded: the gather path gathers local-head
pages, and the Pallas kernel runs per-shard with its grid's head
dimension sized H/tp (the kernel is shape-polymorphic in H — no
kernel change). Greedy tokens stay bit-identical to ``lm_decode`` AND
to the tp=1 engine (tests/test_serve_engine.py; ``serve_bench
--ab-tp`` gates it in CI): each chip's dot products are exactly the
dense math's column slices, psums only add terms the dense contraction
adds, and argmax sees the identical full-vocab row.

**Speculative decoding** (``ServeConfig.speculate_k``): the compiled
step becomes :func:`serve_step_spec` — the layer-skip draft (the
target's first ``draft_layers`` layers sharing embed/head AND the
target's own KV pages, :func:`models.parallel_lm.draft_params`)
proposes up to ``k`` tokens per slot in one ``lax.scan``, and the
target verifies all ``k+1`` positions in ONE rectangular-causal pass
(``q_offset=t, k_offset=0`` — the prefill lane's exact contract). The
host keeps the longest draft/target-agreeing prefix per slot
(:func:`~horovod_tpu.serve.sampling.speculative_accept`) and emits
1..k+1 tokens per tick; rejected rows roll back by page-table
arithmetic (stale rows are overwritten by the next window or causally
masked — no erasure pass), with ``Request.spec_window`` clamping the
window inside the page grant and ``_cow_guard`` widened over the full
write range. Greedy streams stay bit-identical to ``lm_decode`` and
to the non-speculative engine — every emitted token is a target
argmax of its true prefix — across both attention modes and under TP
(tests/test_serve_engine.py; ``serve_bench --ab-spec`` gates it in
CI; hvdverify ``serve.step_spec{,_paged,_tp}`` pin the no-donation
rollback substrate).
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.serve.config import ServeConfig
from horovod_tpu.serve.kvcache import PagedKVCache
from horovod_tpu.serve.scheduler import (
    Request,
    RequestState,
    Scheduler,
    pick_victim,
)

# --------------------------------------------------------------------------
# The compiled step program (pure; jitted once per variant).


def _gather_cache(pages_arr, table):
    """pages [P, ps, H, D] x table [pps] -> the request's contiguous
    logical cache [Lmax, H, D] (unmapped slots read the null page's
    zeros — always masked downstream). Single-array form, kept as the
    paged kernel's exactness reference; the hot path shares one index
    computation for K and V via :func:`_gather_cache_kv`."""
    g = pages_arr[table]
    return g.reshape(g.shape[0] * g.shape[1], g.shape[2], g.shape[3])


def _gather_cache_kv(pk, pv, table):
    """The K AND V gathers of one lane through ONE shared index
    computation: the page table expands to flat row indices once, and
    both page arrays gather through the same vector (the old path
    rebuilt the expansion four times per layer — K/V x decode/prefill;
    tables are the only index input, so K and V always shared it
    logically). Returns ``(k [Lmax, H, D], v [Lmax, H, D])``."""
    import jax.numpy as jnp

    P, ps = pk.shape[0], pk.shape[1]
    rows = (table[:, None] * ps
            + jnp.arange(ps, dtype=table.dtype)[None, :]).reshape(-1)
    return (pk.reshape(P * ps, pk.shape[2], pk.shape[3])[rows],
            pv.reshape(P * ps, pv.shape[2], pv.shape[3])[rows])


def _prefill_lane(params: Dict, pages, pre, *, page_size: int, tp=None,
                  vocab_parallel: bool = False):
    """The chunked-prefill pass of one step — shared verbatim by
    :func:`serve_step` and :func:`serve_step_spec`: one rectangular-
    causal chunk (queries at ``start..start+C-1`` over the full
    gathered cache, ``q_offset=start, k_offset=0``) whose K/V rows
    write through the page table via :func:`~horovod_tpu.serve.
    kvcache.append_rows` (padded rows hit the OOB sentinel and drop).
    Returns ``(new_pages, pre_logits [V])``."""
    import math

    import jax.numpy as jnp
    from jax import lax

    from horovod_tpu.models.parallel_lm import (
        _attn_out_residual,
        _ffn_residual,
        _logits,
        _project_qkv,
    )
    from horovod_tpu.ops.attention import dot_product_attention
    from horovod_tpu.serve.kvcache import append_rows

    ps = page_size
    num_pages = pages[0]["k"].shape[0]
    lmax = pre["table"].shape[0] * ps
    C = pre["tokens"].shape[0]
    start = pre["start"]
    rows = jnp.arange(C)
    row_valid = rows < pre["length"]
    # OOB sentinel drops padded/inactive rows at every scatter.
    write_page, write_off, safe_pos = append_rows(
        pre["table"], start, C, page_size=ps, num_pages=num_pages,
        valid=row_valid)
    xp = params["embed"][pre["tokens"]][None] + \
        params["pos"][safe_pos][None]                  # [1, C, E]
    new_pages = []
    for layer, page in zip(params["layers"], pages):
        pk, pv = page["k"], page["v"]
        qp, kp, vp = _project_qkv(layer, xp, tp)       # [1, C, H, D]
        # math.sqrt, exactly parallel_lm's spelling — the scale
        # must be the bit-identical float for the exactness pin.
        scale = 1.0 / math.sqrt(qp.shape[-1])
        gk, gv = _gather_cache_kv(pk, pv, pre["table"])
        # The chunk's own rows enter the gathered view (scatter —
        # row-distinct indices, padded rows dropped), then the
        # rectangular-causal attention: queries at start+i over
        # keys 0..start+i.
        ck = gk.at[jnp.where(row_valid, safe_pos, lmax)].set(
            kp[0], mode="drop")
        cv = gv.at[jnp.where(row_valid, safe_pos, lmax)].set(
            vp[0], mode="drop")
        attn = dot_product_attention(qp, ck[None], cv[None],
                                     causal=True, scale=scale,
                                     q_offset=start, k_offset=0)
        xp = _attn_out_residual(layer, attn, xp, tp)
        xp = _ffn_residual(layer, xp, tp)
        pk = pk.at[write_page, write_off].set(kp[0], mode="drop")
        pv = pv.at[write_page, write_off].set(vp[0], mode="drop")
        new_pages.append({"k": pk, "v": pv})
    last = jnp.clip(pre["length"] - 1, 0, C - 1)
    row = lax.dynamic_slice_in_dim(xp[0], last, 1, 0)   # [1, E]
    pre_logits = _logits(params, row[None], tp,
                         vocab_parallel)[0, 0]          # [V]
    return new_pages, pre_logits


#: Public alias: under disaggregated serving (FleetConfig.pools) a
#: prefill replica's steady-state tick IS the chunked-prefill lane —
#: every request it admits carries prefill_only, so the decode slots
#: never fill. hvdverify registers this as ``serve.step_prefill_pool``
#: and machine-checks the no-donation invariant on it directly: the
#: finished pages park in the handoff bay until the decode pool's
#: import digest-verifies them, so they must stay readable.
serve_step_prefill = _prefill_lane


def serve_step(params: Dict, pages, dec, pre, *, page_size: int,
               attention: str = "gather", tp=None,
               vocab_parallel: bool = False):
    """One continuous-batching step.

    ``dec``: ``tok``/``pos``/``active`` [S] + ``tables`` [S, pps];
    ``pre`` (or None for the decode-only variant): ``tokens`` [C],
    ``start``/``length`` scalars + ``table`` [pps].
    Returns ``(new_pages, dec_logits [S, V], pre_logits [V] | None)``.

    ``attention`` (static) picks the decode lane's cache path:
    ``gather`` reconstructs the dense per-slot cache and inserts the
    new row into the gathered copy (the exactness reference);
    ``paged`` scatters the new row into its page FIRST (the identical
    scatter — so the kernel stays READ-ONLY over pages and the
    no-donation invariant is untouched) and then streams only the live
    pages through :func:`~horovod_tpu.ops.paged_attention.
    paged_attention_decode`. The prefill lane keeps the full gather in
    both modes (rectangular-causal over the whole cache).

    ``tp`` (static) names the tensor axis when the step runs inside
    ``shard_map`` over head-sharded params and pages; ``vocab_parallel``
    additionally expects a column-sharded head [E, V/tp] and assembles
    full-vocab logits with one tiled all-gather — the sampler upstream
    never sees a shard.
    """
    import math

    import jax
    import jax.numpy as jnp
    from jax import lax

    from horovod_tpu.models.parallel_lm import (
        _attn_out_residual,
        _ffn_residual,
        _logits,
        _project_qkv,
    )
    from horovod_tpu.ops.attention import dot_product_attention
    from horovod_tpu.ops.paged_attention import paged_attention_decode

    if attention not in ("gather", "paged"):
        raise ValueError(
            f"attention must be 'gather' or 'paged', got {attention!r}")
    ps = page_size
    num_pages = pages[0]["k"].shape[0]
    S = dec["tok"].shape[0]
    new_pages = []

    # ---------------------------------------------------- prefill lane
    pre_logits = None
    if pre is not None:
        pages, pre_logits = _prefill_lane(params, pages, pre,
                                          page_size=ps, tp=tp,
                                          vocab_parallel=vocab_parallel)

    # ----------------------------------------------------- decode lane
    t = dec["pos"]                                      # [S]
    write_page_d = jnp.where(dec["active"],
                             dec["tables"][jnp.arange(S), t // ps],
                             num_pages)                 # OOB = dropped
    write_off_d = t % ps
    # Live keys per slot for the paged kernel (t+1; 0 = idle lane).
    lens = jnp.where(dec["active"], t + 1, 0).astype(jnp.int32)
    xd = params["embed"][dec["tok"]][:, None] + \
        params["pos"][t][:, None]                       # [S, 1, E]

    insert = jax.vmap(
        lambda c, u, tt: lax.dynamic_update_slice_in_dim(c, u, tt, 0))

    for layer, page in zip(params["layers"], pages):
        pk, pv = page["k"], page["v"]
        qd, kd, vd = _project_qkv(layer, xd, tp)        # [S, 1, H, D]
        scale = 1.0 / math.sqrt(qd.shape[-1])
        if attention == "paged":
            # Scatter the new row FIRST (the gather path's identical
            # scatter, just hoisted above the attention), then stream
            # only the live pages — the kernel reads position t back
            # from its page, so the dense [S, Lmax, H, D] intermediate
            # never exists and per-step K/V bytes are O(t), not
            # O(Lmax). Read-only kernel over pages: the no-donation
            # invariant is exactly the gather path's.
            pk = pk.at[write_page_d, write_off_d].set(kd[:, 0],
                                                      mode="drop")
            pv = pv.at[write_page_d, write_off_d].set(vd[:, 0],
                                                      mode="drop")
            attn = paged_attention_decode(
                qd[:, 0], pk, pv, dec["tables"], lens,
                scale=scale)[:, None]                   # [S, 1, H, D]
        else:
            gkd, gvd = jax.vmap(
                _gather_cache_kv, in_axes=(None, None, 0))(
                pk, pv, dec["tables"])                  # [S, Lmax, H, D]
            ckd = insert(gkd, kd, t)
            cvd = insert(gvd, vd, t)
            attn = jax.vmap(
                lambda q, k, v, tt: dot_product_attention(
                    q, k, v, causal=True, scale=scale, q_offset=tt)
            )(qd, ckd, cvd, t)                          # [S, 1, H, D]
        xd = _attn_out_residual(layer, attn, xd, tp)
        xd = _ffn_residual(layer, xd, tp)
        if attention != "paged":
            pk = pk.at[write_page_d, write_off_d].set(kd[:, 0],
                                                      mode="drop")
            pv = pv.at[write_page_d, write_off_d].set(vd[:, 0],
                                                      mode="drop")

        new_pages.append({"k": pk, "v": pv})

    dec_logits = _logits(params, xd, tp, vocab_parallel)[:, 0]  # [S, V]
    return new_pages, dec_logits, pre_logits


def serve_step_spec(params: Dict, pages, dec, pre, *, k: int,
                    draft_layers: int, page_size: int,
                    attention: str = "gather", tp=None,
                    vocab_parallel: bool = False):
    """One continuous-batching step with SPECULATIVE decoding: the
    layer-skip draft (the target's first ``draft_layers`` layers
    sharing embed/head) proposes up to ``k`` tokens per slot, and the
    target verifies all ``k+1`` positions in ONE rectangular-causal
    pass — the exact chunked-prefill shape per slot: queries at
    ``t..t+k`` over the full gathered cache, ``q_offset=t,
    k_offset=0``.

    ``dec`` extends :func:`serve_step`'s batch with the speculation
    plane: ``width`` [S] (``k_eff+1`` rows this slot verifies this
    tick — the host's budget clamp; 0 = idle lane) plus the draft's
    sampling knobs ``temp``/``topk``/``seed``/``sidx`` [S] — proposals
    are drawn IN-step, because the propose loop must feed each
    proposal to the next draft step. That loop is ONE ``lax.scan``
    (PR-1's windowing trick), so per-tick dispatch cost stays flat in
    ``k``.

    Returns ``(new_pages, ver_logits [S, k+1, V], draft_toks [S, k],
    draft_logits [S, k, V], pre_logits)``; the host applies
    :func:`~horovod_tpu.serve.sampling.speculative_accept` per slot.

    The verify window's K/V rows scatter through
    :func:`~horovod_tpu.serve.kvcache.append_rows` under the width
    mask — rows past a slot's clamp (and idle lanes) hit the OOB
    sentinel and never touch a real page — and REJECTED rows need no
    rollback pass: a stale position is either overwritten by a later
    window or causally masked (no query ever admits a key past its own
    position), and the host's ``_cow_guard`` copied any shared page
    across the whole write range BEFORE the step, so a rejected row
    can never have landed on another request's page. Pages thread
    functionally, never donated (hvdverify ``serve.step_spec``).

    ``attention`` shapes the DRAFT propose scan: ``gather`` runs the
    ``k`` single-token draft steps over per-slot gathered dense
    caches; ``paged`` scatters each draft row and streams only live
    pages through the fused kernel per step. The verify pass gathers
    in both modes (rectangular-causal over the whole cache — exactly
    the prefill lane's policy). Greedy streams are bit-identical
    either way, and to :func:`serve_step`'s.
    """
    import math

    import jax
    import jax.numpy as jnp
    from jax import lax

    from horovod_tpu.models.parallel_lm import (
        _attn_out_residual,
        _ffn_residual,
        _logits,
        _project_qkv,
    )
    from horovod_tpu.ops.attention import dot_product_attention
    from horovod_tpu.ops.paged_attention import paged_attention_decode
    from horovod_tpu.serve.kvcache import append_rows
    from horovod_tpu.serve.sampling import draft_sample_tokens

    if attention not in ("gather", "paged"):
        raise ValueError(
            f"attention must be 'gather' or 'paged', got {attention!r}")
    if k < 1:
        raise ValueError(f"speculate_k must be >= 1 in-step, got {k}")
    if not 1 <= draft_layers <= len(params["layers"]):
        raise ValueError(
            f"draft_layers={draft_layers} outside 1.."
            f"{len(params['layers'])}")
    ps = page_size
    num_pages = pages[0]["k"].shape[0]
    pps = dec["tables"].shape[1]
    lmax = pps * ps
    S = dec["tok"].shape[0]
    w = k + 1

    # ---------------------------------------------------- prefill lane
    pre_logits = None
    if pre is not None:
        pages, pre_logits = _prefill_lane(params, pages, pre,
                                          page_size=ps, tp=tp,
                                          vocab_parallel=vocab_parallel)

    t = dec["pos"]                                      # [S]
    width = dec["width"]                                # [S]; 0 = idle
    rows = jnp.arange(w)
    insert = jax.vmap(
        lambda c, u, tt: lax.dynamic_update_slice_in_dim(c, u, tt, 0))
    dlayers = params["layers"][:draft_layers]

    # ----------------------------------------------- draft propose scan
    # k single-token draft steps, one lax.scan; step i feeds token c_i
    # (c_0 = the last emitted token, c_i = proposal i) at position t+i
    # and proposes c_{i+1}. Rows the budget clamp masked off propose
    # garbage the host never reads.
    if attention == "paged":
        # Each draft step scatters its row (width-masked — a masked
        # row must never touch a real page) and streams only the live
        # pages through the fused kernel; the pages thread through the
        # scan carry so the verify pass below overwrites every row the
        # draft wrote (same tokens, all layers).
        def draft_step(carry, i):
            tok, dpages = carry
            pos = t + i                                 # [S]
            safe = jnp.clip(pos, 0, lmax - 1)
            x = params["embed"][tok][:, None] + \
                params["pos"][safe][:, None]            # [S, 1, E]
            # A draft row is needed only while a LATER proposal still
            # attends it: the last proposal row is width-2.
            ok = (i + 1) < width
            wp = jnp.where(ok,
                           dec["tables"][jnp.arange(S), safe // ps],
                           num_pages)
            wo = safe % ps
            lens = jnp.where(ok, pos + 1, 0).astype(jnp.int32)
            new_dpages = []
            for layer, (pk, pv) in zip(dlayers, dpages):
                q, kk, vv = _project_qkv(layer, x, tp)  # [S, 1, H, D]
                scale = 1.0 / math.sqrt(q.shape[-1])
                pk = pk.at[wp, wo].set(kk[:, 0], mode="drop")
                pv = pv.at[wp, wo].set(vv[:, 0], mode="drop")
                attn = paged_attention_decode(
                    q[:, 0], pk, pv, dec["tables"], lens,
                    scale=scale)[:, None]               # [S, 1, H, D]
                x = _attn_out_residual(layer, attn, x, tp)
                x = _ffn_residual(layer, x, tp)
                new_dpages.append((pk, pv))
            lg = _logits(params, x, tp, vocab_parallel)[:, 0]
            nxt = draft_sample_tokens(lg, dec["temp"], dec["topk"],
                                      dec["seed"], dec["sidx"] + i)
            return (nxt, tuple(new_dpages)), (nxt, lg)

        carry0 = (dec["tok"],
                  tuple((p["k"], p["v"]) for p in pages[:draft_layers]))
        (_, dpages), (draft_toks, draft_logits) = lax.scan(
            draft_step, carry0, jnp.arange(k))
        pages = [{"k": pk, "v": pv} for pk, pv in dpages] + \
            list(pages[draft_layers:])
    else:
        # Gather each draft layer's dense per-slot caches ONCE; the
        # scan inserts each step's row into the gathered copies (the
        # decode lane's exact idiom) and the copies are DISCARDED
        # after — the verify pass owns every row that persists.
        gks, gvs = [], []
        for page in pages[:draft_layers]:
            a, b = jax.vmap(_gather_cache_kv, in_axes=(None, None, 0))(
                page["k"], page["v"], dec["tables"])
            gks.append(a)
            gvs.append(b)

        def draft_step(carry, i):
            tok, dck, dcv = carry
            pos = t + i                                 # [S]
            safe = jnp.clip(pos, 0, lmax - 1)
            x = params["embed"][tok][:, None] + \
                params["pos"][safe][:, None]            # [S, 1, E]
            new_ck, new_cv = [], []
            for layer, ck0, cv0 in zip(dlayers, dck, dcv):
                q, kk, vv = _project_qkv(layer, x, tp)  # [S, 1, H, D]
                scale = 1.0 / math.sqrt(q.shape[-1])
                ck = insert(ck0, kk, safe)
                cv = insert(cv0, vv, safe)
                new_ck.append(ck)
                new_cv.append(cv)
                attn = jax.vmap(
                    lambda q1, k1, v1, tt: dot_product_attention(
                        q1, k1, v1, causal=True, scale=scale,
                        q_offset=tt)
                )(q, ck, cv, safe)                      # [S, 1, H, D]
                x = _attn_out_residual(layer, attn, x, tp)
                x = _ffn_residual(layer, x, tp)
            lg = _logits(params, x, tp, vocab_parallel)[:, 0]
            nxt = draft_sample_tokens(lg, dec["temp"], dec["topk"],
                                      dec["seed"], dec["sidx"] + i)
            return (nxt, tuple(new_ck), tuple(new_cv)), (nxt, lg)

        (_, _, _), (draft_toks, draft_logits) = lax.scan(
            draft_step, (dec["tok"], tuple(gks), tuple(gvs)),
            jnp.arange(k))

    draft_toks = jnp.swapaxes(draft_toks, 0, 1)         # [S, k]
    draft_logits = jnp.swapaxes(draft_logits, 0, 1)     # [S, k, V]

    # ------------------------------------------------------ verify pass
    # Window = [last emitted token, proposals] at positions t..t+k per
    # slot; ONE rectangular-causal target pass over the gathered cache
    # yields logits at every position. Width-masked rows gather-insert
    # to the Lmax drop index and page-scatter to the OOB sentinel.
    toks_w = jnp.concatenate([dec["tok"][:, None], draft_toks], 1)
    wp, wo, safe_w = jax.vmap(
        lambda tab, tt, wd: append_rows(
            tab, tt, w, page_size=ps, num_pages=num_pages,
            valid=jnp.arange(w) < wd))(dec["tables"], t, width)
    xw = params["embed"][toks_w] + params["pos"][safe_w]  # [S, w, E]
    gather_idx = jnp.where(rows[None, :] < width[:, None],
                           safe_w, lmax)                 # [S, w]
    scatter_g = jax.vmap(
        lambda g, ii, u: g.at[ii].set(u, mode="drop"))
    new_pages = []
    for layer, page in zip(params["layers"], pages):
        pk, pv = page["k"], page["v"]
        qw, kw, vw = _project_qkv(layer, xw, tp)         # [S, w, H, D]
        scale = 1.0 / math.sqrt(qw.shape[-1])
        gk, gv = jax.vmap(_gather_cache_kv, in_axes=(None, None, 0))(
            pk, pv, dec["tables"])                       # [S, Lmax, H, D]
        ck = scatter_g(gk, gather_idx, kw)
        cv = scatter_g(gv, gather_idx, vw)
        attn = jax.vmap(
            lambda q1, k1, v1, tt: dot_product_attention(
                q1, k1, v1, causal=True, scale=scale,
                q_offset=tt, k_offset=0)
        )(qw, ck, cv, t)                                 # [S, w, H, D]
        xw = _attn_out_residual(layer, attn, xw, tp)
        xw = _ffn_residual(layer, xw, tp)
        pk = pk.at[wp, wo].set(kw, mode="drop")
        pv = pv.at[wp, wo].set(vw, mode="drop")
        new_pages.append({"k": pk, "v": pv})

    ver_logits = _logits(params, xw, tp, vocab_parallel)  # [S, w, V]
    return new_pages, ver_logits, draft_toks, draft_logits, pre_logits


# --------------------------------------------------------------------------
# The host-side engine.


def resolve_tp_mesh(params: Dict, config: ServeConfig):
    """Bind ``config.mesh`` to this host's devices; fail-fast on
    everything the config string alone could not know. Returns
    ``(logical_mesh, tp_axis, tp_degree)`` — ``(None, None, 1)`` when
    the engine runs unsharded (``mesh=None`` or an all-ones mesh).

    Raises :class:`~horovod_tpu.common.exceptions.InvalidArgumentError`
    at ENGINE construction, never at first compile, when the mesh's
    device product exceeds the available devices (LogicalMesh's own
    check) or when heads / MLP features / vocab don't divide the tp
    degree (the shard shapes would be ragged)."""
    axes = config.mesh_axes()
    if not axes:
        return None, None, 1
    import jax

    from horovod_tpu.common.exceptions import InvalidArgumentError
    from horovod_tpu.parallel.logical import LogicalMesh

    lm = LogicalMesh.from_config(config.mesh, devices=jax.devices())
    tp_axis = lm.role_axis("tensor")
    tp = lm.axes.get(tp_axis, 1)
    if tp == 1:
        return None, None, 1
    layer0 = params["layers"][0]
    dims = (("num_heads", int(layer0["wqkv"].shape[2])),
            ("mlp", int(layer0["wup"].shape[1])),
            ("vocab", int(params["head"].shape[1])))
    for what, n in dims:
        if n % tp:
            raise InvalidArgumentError(
                f"ServeConfig.mesh {config.mesh!r}: {what}={n} is not "
                f"divisible by tp={tp} — the head/feature/vocab shards "
                "must split exactly (pad the model or pick a tp that "
                "divides)")
    return lm, tp_axis, tp


class ServeEngine:
    """Continuous-batching LM serving over a paged KV cache.

    ``params`` is :func:`models.parallel_lm.init_lm_params`' pytree.
    The engine owns the device page arrays, the scheduler, and the
    request lifecycle; :meth:`submit` queues work, :meth:`step` runs
    one compiled step (returns False when fully idle), :meth:`run`
    drains to idle. ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, params: Dict, config: ServeConfig, *,
                 chips: int = 1, clock=time.perf_counter):
        self.config = config
        self.chips = chips
        self.clock = clock
        #: Bound LogicalMesh + tensor axis + degree (mesh=None -> tp=1).
        #: Fail-fast happens HERE (device budget, divisibility), never
        #: at first compile.
        self.logical_mesh, self._tp_axis, self.tp = \
            resolve_tp_mesh(params, config)
        kv_sharding = None
        self._param_specs = None
        if self.tp > 1:
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from horovod_tpu.models.parallel_lm import lm_param_specs

            mesh = self.logical_mesh.mesh
            # Megatron param placement + head-sharded pages: the DATA
            # plane. Specs double as the shard_map in/out_specs below.
            self._param_specs = lm_param_specs(
                len(params["layers"]), self._tp_axis,
                vocab_parallel=True)
            params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, self._param_specs)
            self._kv_spec = P(None, None, self._tp_axis, None)
            kv_sharding = NamedSharding(mesh, self._kv_spec)
        self.params = params
        #: Speculative decoding plane (``config.speculate_k`` > 0):
        #: static k compiled into the step, layer-skip draft depth
        #: resolved against THIS model (0 = auto: half the depth, at
        #: least 1) — fail-fast at construction, never at first
        #: compile, like the tp divisibility checks above.
        self.spec_k = int(config.speculate_k)
        self.draft_layers = 0
        if self.spec_k:
            from horovod_tpu.common.exceptions import (
                InvalidArgumentError,
            )

            n_layers = len(params["layers"])
            dl = config.draft_layers or max(1, n_layers // 2)
            if not 1 <= dl <= n_layers:
                raise InvalidArgumentError(
                    f"ServeConfig.draft_layers={config.draft_layers}: "
                    f"the layer-skip draft is a prefix of the target's "
                    f"{n_layers} layers — need 1..{n_layers}")
            self.draft_layers = dl
        self.cache = PagedKVCache(params, config,
                                  kv_sharding=kv_sharding)
        if config.prefix_caching:
            from horovod_tpu.serve.prefix import PrefixIndex

            #: Radix prefix index (serve/prefix.py) — admission maps a
            #: prompt's already-filled pages read-only, prefill starts
            #: at the first miss.
            self.prefix = PrefixIndex(self.cache.allocator,
                                      config.page_size)
        else:
            self.prefix = None
        self.scheduler = Scheduler(self.cache, config,
                                   prefix=self.prefix)
        #: Copy-on-write page copies performed (the backstop — 0 in
        #: normal operation; see :meth:`_cow_guard`).
        self.cow_copies = 0
        self.slots: List[Optional[Request]] = [None] * config.decode_slots
        self.ready: List[Request] = []      # prefilled, awaiting a slot
        self.prefilling: Optional[Request] = None
        #: Disaggregated-serving handoff bay: ``prefill_only`` requests
        #: parked fully prefilled (first token emitted, pages held)
        #: until the fleet ships their KV pages to a decode replica —
        #: :meth:`export_handoff` / :meth:`release_handoff` on this
        #: side, :meth:`admit_prefilled` on the receiving one. Parked
        #: requests never decode here (the serve loop skips the bay),
        #: but they count in_flight and their deadlines still sweep.
        self.handoff: List[Request] = []
        self.finished: List[Request] = []
        self.evicted: List[Request] = []    # terminal (requeue off)
        self.timed_out: List[Request] = []  # terminal (deadline passed)
        self.occupancy_samples: List[float] = []
        #: Per-step decode-lane live-key counts (t+1 per slot, 0 =
        #: idle lane) — the raw input :func:`ops.paged_attention.
        #: paged_grid_info` aggregates into stats()["attention"], so
        #: serve_bench records carry the gather-vs-paged byte evidence
        #: on BOTH sides of the A/B (one accounting model, owned by
        #: paged_grid_info). Kept per-step (not pre-summed) so tests
        #: can pin the exact page walk; stats() aggregation is
        #: O(steps) — bench runs call it once at the end, and
        #: reset_metrics() bounds a long-lived engine.
        self.attn_len_samples: List[List[int]] = []
        self.steps = 0
        #: Speculation accounting (speculate_k > 0): per decode TICK,
        #: proposals made/accepted and tokens emitted — the inputs to
        #: stats()["spec"] (accept_rate, tokens_per_step).
        self.spec_ticks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self._t_start = clock()
        if self.spec_k:
            step = functools.partial(serve_step_spec,
                                     k=self.spec_k,
                                     draft_layers=self.draft_layers,
                                     page_size=config.page_size,
                                     attention=config.attention,
                                     tp=self._tp_axis,
                                     vocab_parallel=self.tp > 1)
        else:
            step = functools.partial(serve_step,
                                     page_size=config.page_size,
                                     attention=config.attention,
                                     tp=self._tp_axis,
                                     vocab_parallel=self.tp > 1)
        import jax

        # Two fixed-shape variants, compiled once each; NO donation —
        # live requests hold pages under the step (hvdverify
        # serve.step forbid_donation; the tp variants serve.step_tp
        # keep the same invariant — shards of a live page must stay
        # readable under the step on every chip).
        if self.tp > 1:
            from jax.sharding import PartitionSpec as P

            from horovod_tpu.parallel.spmd import (
                _SHARD_MAP_CHECK_KW,
                _shard_map,
            )

            mesh = self.logical_mesh.mesh
            kv = self._kv_spec
            # dec/pre arrive replicated (P() prefix over the host
            # dicts), pages head-sharded in AND out, logits replicated
            # full-vocab (the step's all-gather makes them so).
            untyped = {_SHARD_MAP_CHECK_KW: False}
            # The spec step returns (pages, ver_logits, draft_toks,
            # draft_logits, pre_logits) — two extra replicated outputs
            # over the base step's (pages, dec_logits, pre_logits).
            n_rep = 4 if self.spec_k else 2
            self._step_mixed = jax.jit(_shard_map(
                lambda p, pages, dec, pre: step(p, pages, dec, pre),
                mesh=mesh,
                in_specs=(self._param_specs, kv, P(), P()),
                out_specs=(kv,) + (P(),) * n_rep, **untyped))
            self._step_decode = jax.jit(_shard_map(
                lambda p, pages, dec: step(p, pages, dec, None),
                mesh=mesh,
                in_specs=(self._param_specs, kv, P()),
                out_specs=(kv,) + (P(),) * n_rep, **untyped))
        else:
            self._step_mixed = jax.jit(step)
            self._step_decode = jax.jit(
                lambda params, pages, dec: step(params, pages, dec,
                                                None))

    # ------------------------------------------------------ submission

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_token: Optional[int] = None, seed: int = 0,
               arrival: Optional[float] = None,
               ttl: Optional[float] = None) -> Request:
        """Queue one generation request; returns it (check ``state`` —
        ``rejected`` means it can never run or the queue is full).
        ``ttl`` (seconds from arrival; default ``config.default_ttl``)
        bounds how long the request may live: past it, the request is
        finished with the ``timeout`` status and its pages freed."""
        from horovod_tpu.serve.scheduler import make_request

        req = make_request(self.config, self.clock, prompt,
                           max_new_tokens, temperature=temperature,
                           top_k=top_k, eos_token=eos_token, seed=seed,
                           arrival=arrival, ttl=ttl)
        self.scheduler.submit(req)
        return req

    # ------------------------------------------------------- lifecycle

    @property
    def in_flight(self) -> int:
        return (sum(1 for s in self.slots if s is not None)
                + len(self.ready) + (1 if self.prefilling else 0)
                + len(self.handoff))

    @property
    def idle(self) -> bool:
        return (self.in_flight == 0 and not self.scheduler.queue)

    def _free_slots(self) -> int:
        return sum(1 for s in self.slots if s is None)

    def _finish(self, req: Request) -> None:
        req.state = RequestState.FINISHED
        req.t_finish = self.clock()
        self.scheduler.release(req)
        self.finished.append(req)

    def _do_evict(self, victim: Request) -> None:
        """Release a victim's pages and remove it from service; requeue
        (recompute path) or terminate per config."""
        self._remove_from_service(victim)
        victim.evictions += 1
        victim.state = RequestState.EVICTED
        if self.config.requeue_evicted:
            if not self.scheduler.requeue(victim):
                self._finish(victim)
        else:
            self.evicted.append(victim)

    def _remove_from_service(self, req: Request) -> None:
        """Release the request's pages and detach it from every service
        structure (slots, ready, prefill lane) — the shared half of
        eviction and deadline timeout."""
        self.scheduler.release(req)
        for i, s in enumerate(self.slots):
            if s is req:
                self.slots[i] = None
        self.ready = [r for r in self.ready if r is not req]
        self.handoff = [r for r in self.handoff if r is not req]
        if self.prefilling is req:
            self.prefilling = None

    def _time_out(self, req: Request, now: float) -> None:
        """Deadline epilogue: remove from service, mark terminal.
        Unlike eviction there is no requeue — the client's latency
        budget is already blown; recomputing for a dead stream would
        only steal step time from live ones."""
        self._remove_from_service(req)
        self.scheduler.drop(req)
        req.state = RequestState.TIMEOUT
        req.t_finish = now
        self.timed_out.append(req)

    def _expire_deadlines(self) -> None:
        """Sweep every live request (queued included — a request can
        blow its deadline waiting) at the top of each step; one wedged
        stream can never hold KV pages past its deadline + one step."""
        now = self.clock()
        live = ([s for s in self.slots if s is not None]
                + list(self.ready) + list(self.handoff)
                + ([self.prefilling] if self.prefilling else [])
                + list(self.scheduler.queue))
        for req in live:
            if req.expired(now):
                self._time_out(req, now)

    def _evict_for(self, requester: Request) -> bool:
        """Lazy-mode page pressure: evict the newest-admitted request
        that is not the requester (and not mid-prefill-chunk). False =
        nothing else to evict; the caller evicts the requester.
        Prefix-index-only holds go FIRST — reclaiming a cold cached
        prefix costs a future re-prefill, evicting a live request
        costs a certain recompute — and shared pages are never victims
        either way (a victim's release only frees its exclusively-held
        pages; the refcounted path keeps the rest alive)."""
        if self.prefix is not None and self.prefix.reclaim(1):
            return True
        candidates = [s for s in self.slots if s is not None] + \
            list(self.ready)
        victim = pick_victim(candidates, requester)
        if victim is None:
            return False
        self._do_evict(victim)
        return True

    # ------------------------------------------------------------ step

    def _promote_ready(self) -> None:
        for i in range(len(self.slots)):
            if self.slots[i] is None and self.ready:
                req = self.ready.pop(0)
                req.state = RequestState.DECODE
                self.slots[i] = req

    def _ensure_capacity(self) -> None:
        """Lazy admission: map pages for every position this step
        writes, evicting under pressure (reserve mode pre-granted the
        worst case — nothing to do)."""
        if self.config.admission != "lazy":
            return
        for req in list(self.slots):
            if req is None or req not in self.slots:
                continue
            # Speculation widens the write range: the verify window
            # lands rows t..t+k_eff, so every page under the WHOLE
            # window must be mapped before the step.
            last = req.next_pos + (req.spec_window(self.spec_k)
                                   if self.spec_k else 0)
            if not self.scheduler.ensure_pages(req, last,
                                               self._evict_for):
                self._do_evict(req)
        if self.prefilling is not None:
            req = self.prefilling
            chunk = min(self.config.prefill_chunk,
                        req.prompt_len - req.prefill_pos)
            last = req.prefill_pos + chunk - 1
            if not self.scheduler.ensure_pages(req, last,
                                               self._evict_for):
                self._do_evict(req)

    def _cow_guard(self) -> None:
        """Copy-on-write backstop: no page this step WRITES may be
        shared. By construction it never is — only FULL prompt pages
        are indexable, a match never covers the whole prompt, and both
        prefill (positions >= prefill_pos = matched tokens) and decode
        (positions >= prompt_len) write past every shared slot — so
        this sweep finds nothing in normal operation. It stays because
        a shared write would silently corrupt every OTHER holder's
        stream: any slip in the invariant becomes one counted page
        copy (``cow_copies``) instead of a wrong token."""
        if self.prefix is None:
            return
        for req in self.slots:
            if req is not None and req.generated:
                # Speculative ticks write the whole verify window
                # t..t+k_eff — a rejected row rolled back by page
                # arithmetic must STILL never have landed on a shared
                # page, so the guard covers the full range.
                last = req.next_pos + (req.spec_window(self.spec_k)
                                       if self.spec_k else 0)
                self._cow_range(req, req.next_pos, last)
        if self.prefilling is not None:
            req = self.prefilling
            chunk = min(self.config.prefill_chunk,
                        req.prompt_len - req.prefill_pos)
            self._cow_range(req, req.prefill_pos,
                            req.prefill_pos + chunk - 1)

    def _cow_range(self, req: Request, first_pos: int, last_pos: int
                   ) -> None:
        ps = self.config.page_size
        for slot in range(first_pos // ps, last_pos // ps + 1):
            page = int(req.page_table[slot])
            if page and self.cache.allocator.is_shared(page):
                new = self.cache.cow_page(page)
                req.page_table[slot] = new
                req.pages[req.pages.index(page)] = new
                self.cow_copies += 1

    def _build_dec(self):
        S = self.config.decode_slots
        pps = self.cache.pages_per_seq
        tok = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        tables = np.zeros((S, pps), np.int32)
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            tok[i] = req.generated[-1]
            pos[i] = req.next_pos
            active[i] = True
            tables[i] = req.page_table
        dec = {"tok": tok, "pos": pos, "active": active,
               "tables": tables}
        if self.spec_k:
            # The speculation plane: width = k_eff+1 verify rows per
            # slot (0 = idle lane — it subsumes `active` in the spec
            # step) plus the draft's in-step sampling knobs.
            width = np.zeros((S,), np.int32)
            temp = np.zeros((S,), np.float32)
            topk = np.zeros((S,), np.int32)
            seed = np.zeros((S,), np.int32)
            sidx = np.zeros((S,), np.int32)
            for i, req in enumerate(self.slots):
                if req is None:
                    continue
                width[i] = req.spec_window(self.spec_k) + 1
                temp[i] = req.temperature
                topk[i] = req.top_k
                seed[i] = req.seed
                sidx[i] = req.sample_index
            dec.update(width=width, temp=temp, topk=topk, seed=seed,
                       sidx=sidx)
        return dec

    def _build_pre(self):
        if self.prefilling is None:
            return None, 0
        req = self.prefilling
        C = self.config.prefill_chunk
        chunk = min(C, req.prompt_len - req.prefill_pos)
        tokens = np.zeros((C,), np.int32)
        tokens[:chunk] = req.prompt[req.prefill_pos:
                                    req.prefill_pos + chunk]
        # page_table is never None here: Scheduler._admit assigns it
        # before pick_prefill returns the request.
        return {
            "tokens": tokens,
            "start": np.int32(req.prefill_pos),
            "length": np.int32(chunk),
            "table": np.asarray(req.page_table, np.int32),
        }, chunk

    def step(self) -> bool:
        """Run one compiled step; False when there was nothing to do
        (no active requests and nothing admissible in the queue)."""
        from horovod_tpu.serve.sampling import sample_tokens

        self._expire_deadlines()
        self._promote_ready()
        if self.prefilling is None:
            self.prefilling = self.scheduler.pick_prefill(
                self._free_slots(), self.in_flight)
            if self.prefilling is not None:
                # (Re-)admission stamp — pick_victim's newest-admitted-
                # first eviction order keys on this.
                self.prefilling.t_admit = self.clock()
        self._ensure_capacity()
        # Eviction may have freed slots: promote, then re-map pages for
        # the newly promoted rows. A promoted request whose next write
        # starts a fresh page slot must not reach the compiled step
        # with an unmapped (0) table entry — that row would write into
        # the reserved null page and silently corrupt its KV stream.
        # Terminates: each pass pops at least one request off `ready`
        # (evictions requeue to the scheduler, never back onto ready).
        while self.ready and any(s is None for s in self.slots):
            self._promote_ready()
            self._ensure_capacity()
        if self.prefilling is None and \
                all(s is None for s in self.slots):
            return False

        self._cow_guard()
        dec = self._build_dec()
        pre, chunk = self._build_pre()
        # Static traffic accounting for this step's decode lane (live
        # keys per slot = t+1; under speculation the verify window
        # extends the read range to t+k_eff, so live keys =
        # next_pos + spec_window + 1) — pure host data, no device sync.
        self.attn_len_samples.append(
            [0 if r is None else
             r.next_pos + (r.spec_window(self.spec_k)
                           if self.spec_k else 0) + 1
             for r in self.slots])

        import jax.numpy as jnp

        S = self.config.decode_slots
        pre_done = (self.prefilling is not None and
                    self.prefilling.prefill_pos + chunk
                    >= self.prefilling.prompt_len)

        if self.spec_k:
            from horovod_tpu.serve.sampling import speculative_accept

            if pre is None:
                pages, ver_logits, draft_toks, draft_logits, _ = \
                    self._step_decode(self.params, self.cache.pages,
                                      dec)
                pre_logits = None
            else:
                (pages, ver_logits, draft_toks, draft_logits,
                 pre_logits) = self._step_mixed(
                    self.params, self.cache.pages, dec, pre)
            self.cache.pages = pages

            ver = np.asarray(ver_logits)        # [S, k+1, V]
            dts = np.asarray(draft_toks)        # [S, k]
            dls = np.asarray(draft_logits)      # [S, k, V]
            pre_token = None
            if pre_logits is not None and pre_done:
                # The prefill lane's FIRST token is a plain 1-row
                # non-speculative draw — same sampler, same key.
                preq = self.prefilling
                pre_token = int(np.asarray(sample_tokens(
                    jnp.asarray(pre_logits)[None],
                    np.asarray([preq.temperature], np.float32),
                    np.asarray([preq.top_k], np.int32),
                    np.asarray([preq.seed], np.int32),
                    np.asarray([preq.sample_index], np.int32)))[0])
            now = self.clock()      # after the d2h pull: a real sync

            for i in range(S):
                req = self.slots[i]
                if req is None:
                    continue
                wd = int(dec["width"][i])
                emitted = speculative_accept(
                    ver[i, :wd], dts[i, :wd - 1], dls[i, :wd - 1],
                    temperature=float(req.temperature),
                    top_k=int(req.top_k), seed=int(req.seed),
                    position0=int(req.sample_index))
                self.spec_ticks += 1
                self.spec_proposed += wd - 1
                self.spec_accepted += len(emitted) - 1
                for tok in emitted:
                    self.spec_emitted += 1
                    self._accept_token(req, int(tok), now)
                    if req.state == RequestState.FINISHED:
                        # EOS (or the budget) mid-window: later
                        # emitted tokens are dropped; the stale KV
                        # rows past the cut go with the request's
                        # pages.
                        break
                if req.state == RequestState.FINISHED:
                    self.slots[i] = None
        else:
            if pre is None:
                pages, dec_logits, _ = self._step_decode(
                    self.params, self.cache.pages, dec)
                pre_logits = None
            else:
                pages, dec_logits, pre_logits = self._step_mixed(
                    self.params, self.cache.pages, dec, pre)
            self.cache.pages = pages

            # One sampler call covers the decode slots + the prefill
            # lane.
            rows = list(self.slots)
            logits = dec_logits
            if pre_logits is not None:
                rows = rows + [self.prefilling if pre_done else None]
                logits = jnp.concatenate(
                    [dec_logits, pre_logits[None]], 0)
            n = len(rows)
            temp = np.zeros((n,), np.float32)
            topk = np.zeros((n,), np.int32)
            seeds = np.zeros((n,), np.int32)
            positions = np.zeros((n,), np.int32)
            for i, req in enumerate(rows):
                if req is None:
                    continue
                temp[i] = req.temperature
                topk[i] = req.top_k
                seeds[i] = req.seed
                positions[i] = req.sample_index
            tokens = np.asarray(sample_tokens(logits, temp, topk,
                                              seeds, positions))
            now = self.clock()      # after the d2h pull: a real sync
            pre_token = (int(tokens[S])
                         if pre_logits is not None and pre_done
                         else None)

            # Decode slots: one new token each.
            for i in range(S):
                req = self.slots[i]
                if req is None:
                    continue
                self._accept_token(req, int(tokens[i]), now)
                if req.state == RequestState.FINISHED:
                    self.slots[i] = None

        # Prefill lane: advance; on completion emit the FIRST token.
        if self.prefilling is not None and pre is not None:
            req = self.prefilling
            req.prefill_pos += chunk
            if pre_done:
                if self.prefix is not None:
                    # Index the now-filled prompt pages BEFORE the
                    # first token can finish the request (max_new=1 —
                    # _finish releases its pages; the insert's retain
                    # must land while the request still holds them).
                    self.prefix.insert(req.prompt, req.page_table)
                self._accept_token(req, pre_token, now)
                self.prefilling = None
                if req.state != RequestState.FINISHED:
                    req.state = RequestState.DECODE
                    if req.prefill_only:
                        # Disaggregated handoff: park fully prefilled
                        # (pages held, first token emitted) until the
                        # fleet ships the KV pages to a decode
                        # replica. A request that finished ON its
                        # first token never reaches here — it needs no
                        # decode pool.
                        self.handoff.append(req)
                    else:
                        self.ready.append(req)

        self.occupancy_samples.append(self.cache.occupancy())
        self.steps += 1
        return True

    def _accept_token(self, req: Request, token: int, now: float
                      ) -> None:
        req.generated.append(token)
        req.output.append(token)
        if req.t_first_token is None:
            req.t_first_token = now
        req.token_times.append(now)
        if req.done_generating or req.hit_eos(self.config.eos_token):
            self._finish(req)

    # ------------------------------------- disaggregated prefill/decode

    def _handoff_req(self, rid: str) -> Request:
        for r in self.handoff:
            if r.rid == rid:
                return r
        raise KeyError(f"no parked handoff request {rid!r} — expired, "
                       "already released, or never parked here")

    def handoff_ready(self) -> List[str]:
        """rids parked in the handoff bay (prefill finished, KV pages
        ready to ship)."""
        return [r.rid for r in self.handoff]

    def export_handoff(self, rid: str) -> bytes:
        """The parked request's finished KV pages as one deterministic
        blob (:meth:`PagedKVCache.export_pages
        <horovod_tpu.serve.kvcache.PagedKVCache.export_pages>` over the
        page-table prefix covering the prompt). READ-ONLY and
        repeatable — a torn transfer re-exports identical bytes, which
        is what makes the chunk stream's resume-from-offset sound."""
        req = self._handoff_req(rid)
        n_exp = self.cache.pages_needed(req.prompt_len, 1)
        pages = [int(req.page_table[j]) for j in range(n_exp)]
        return self.cache.export_pages(pages, req.prompt_len)

    def release_handoff(self, rid: str) -> Request:
        """Drop the prefill side's hold once the decode replica has
        COMMITTED the import: pages release through the refcounted path
        (prefix-shared pages stay alive under the index) and the
        request leaves every service structure WITHOUT a terminal
        event — ownership moved, the stream did not end. Returns the
        request (the inproc fleet re-uses the very same object on the
        decode side)."""
        req = self._handoff_req(rid)
        self.scheduler.release(req)
        self.handoff = [r for r in self.handoff if r is not req]
        return req

    def admit_prefilled(self, req: Request, blob: bytes) -> None:
        """Decode-side handoff admission: import the shipped KV pages
        into THIS cache's allocator, grant the remainder of the
        request's worst-case budget (reserve discipline — admitted
        means it can run to completion), map the page table, and queue
        the request at its handoff position (``ready``, state DECODE —
        the next step promotes it into a slot and decodes token 2
        onward; token 1 was emitted prefill-side). All-or-nothing:
        :class:`~horovod_tpu.serve.kvcache.OutOfPages` or a typed
        geometry :class:`~horovod_tpu.serve.transport.FrameError`
        leaves this engine unchanged, and the caller's handoff stays
        parked on the prefill side (retry or redispatch — never a
        half-admitted request)."""
        from horovod_tpu.serve.transport import FrameError

        imported, positions = self.cache.import_pages(blob)
        try:
            if positions != req.prompt_len:
                raise FrameError(
                    f"handoff blob covers {positions} positions, "
                    f"request prompt is {req.prompt_len} — wrong blob "
                    "for this request")
            total = self.cache.pages_needed(req.prompt_len,
                                            req.max_new_tokens)
            extra = self.cache.allocator.alloc(total - len(imported))
        except BaseException:
            self.cache.allocator.release(imported)
            raise
        req.pages = list(imported) + list(extra)
        req.page_table = np.zeros(self.cache.pages_per_seq, np.int32)
        req.page_table[:total] = np.asarray(req.pages, np.int32)
        req.prefill_pos = req.prompt_len
        req.state = RequestState.DECODE
        req.t_admit = self.clock()
        self.ready.append(req)

    def update_params(self, params: Dict) -> None:
        """Swap the model weights in place — the fleet's rolling-update
        primitive. Only valid when IDLE: a live request's decode must
        never mix weights mid-stream (the fleet drains the replica
        before pushing). Geometry must match the compiled programs'
        shapes, so the jitted step variants re-trace nothing — a
        geometry change is a respawn, not an update."""
        if not self.idle:
            raise RuntimeError(
                "update_params with requests in flight — drain the "
                "engine first (the fleet's rolling update does)")
        old, new = self.params["pos"].shape, params["pos"].shape
        if tuple(old) != tuple(new):
            raise ValueError(
                f"update_params geometry mismatch: position table "
                f"{tuple(new)} vs the engine's {tuple(old)} — a "
                "geometry change needs a fresh engine, not a weight "
                "swap")
        if self.tp > 1:
            # Same placement as construction: the compiled sharded
            # step expects head/feature/vocab shards, not replicas.
            import jax
            from jax.sharding import NamedSharding

            mesh = self.logical_mesh.mesh
            params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, self._param_specs)
        self.params = params
        if self.prefix is not None:
            # K/V rows are a function of the weights: stale-version
            # pages must never serve a new-version request.
            self.prefix.flush()

    # ------------------------------------------------------------- run

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drain to idle (or ``max_steps``); returns requests finished
        so far."""
        while not self.idle:
            if max_steps is not None and self.steps >= max_steps:
                break
            if not self.step():
                break   # queue non-empty but nothing admissible
        return self.finished

    def reset_metrics(self) -> None:
        """Drop completed-work bookkeeping (the bench warmup
        discipline: compile+warm through a dummy request, then measure
        from a clean slate). Only valid when idle."""
        if not self.idle:
            raise RuntimeError("reset_metrics with requests in flight")
        self.finished = []
        self.evicted = []
        self.timed_out = []
        self.scheduler.rejected = []
        self.occupancy_samples = []
        self.attn_len_samples = []
        self.steps = 0
        self.cow_copies = 0
        self.spec_ticks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        if self.prefix is not None:
            self.prefix.reset_metrics()
        self._t_start = self.clock()

    def stats(self) -> Dict:
        """Aggregate SLO metrics over every request seen so far."""
        from horovod_tpu.serve.metrics import summarize

        everything = (self.finished + self.evicted + self.timed_out
                      + self.ready + self.handoff
                      + [s for s in self.slots if s is not None]
                      + ([self.prefilling] if self.prefilling else [])
                      + self.scheduler.queue + self.scheduler.rejected)
        out = summarize(everything, self.clock() - self._t_start,
                        self.chips, self.occupancy_samples)
        out["attention"] = self.attention_stats()
        ps = self.prefix_stats()
        if ps is not None:
            out["prefix"] = ps
        sp = self.spec_stats()
        if sp is not None:
            out["spec"] = sp
        return out

    def spec_stats(self) -> Optional[Dict]:
        """Speculation accounting over the run (None when speculation
        is off — consumers must tolerate the key's absence, exactly
        the ``prefix`` discipline). ``accept_rate`` = accepted
        proposals over draft proposals; ``tokens_per_step`` = tokens
        emitted per per-slot speculative tick — > 1 is the whole point
        (k+1 at a perfect draft, 1 at a useless one: never slower in
        tokens, only in wasted verify FLOPs)."""
        if not self.spec_k:
            return None
        return {
            "k": self.spec_k,
            "draft_layers": self.draft_layers,
            "ticks": self.spec_ticks,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "accept_rate":
                (round(self.spec_accepted / self.spec_proposed, 4)
                 if self.spec_proposed else None),
            "tokens_per_step":
                (round(self.spec_emitted / self.spec_ticks, 4)
                 if self.spec_ticks else None),
        }

    def prefix_stats(self) -> Optional[Dict]:
        """Prefix-cache accounting over the run (None when the cache
        is off — consumers must tolerate the key's absence: pre-prefix
        engines and stub workers never stamp it). ``hit_rate`` is
        hits over ADMITTED requests; ``prefill_tokens_saved`` the
        prompt tokens whose prefill compute a hit skipped."""
        if self.prefix is None:
            return None
        s = self.prefix.stats()
        s["hit_rate"] = (round(s["hits"] / s["lookups"], 4)
                         if s["lookups"] else None)
        s["prefill_tokens_saved"] = s["tokens_hit"]
        s["cow_copies"] = self.cow_copies
        s["pages_shared_now"] = self.cache.allocator.shared
        return s

    def step_grid_info(self, lengths: List[int]) -> Dict:
        """One step's static decode-traffic accounting — exactly
        :func:`ops.paged_attention.paged_grid_info` over this engine's
        cache geometry (the single owner of the byte model)."""
        import numpy as np

        from horovod_tpu.ops.paged_attention import paged_grid_info

        c = self.cache
        return paged_grid_info(
            lengths, page_size=self.config.page_size,
            pages_per_seq=c.pages_per_seq, num_heads=c.num_heads,
            head_dim=c.head_dim,
            dtype_bytes=np.dtype(c.dtype).itemsize,
            num_layers=c.num_layers, tp=self.tp)

    def attention_stats(self) -> Dict:
        """Decode-lane K/V traffic accounting over the run: what the
        paged kernel streams (live pages, ``ceil((t+1)/page_size)``
        per slot) vs what the gather path reconstructs (``Lmax/
        page_size`` pages per slot, every slot every step) — the
        per-step :func:`ops.paged_attention.paged_grid_info` results
        aggregated. Stamped on BOTH modes so the gather/paged A/B is
        honest on both sides; the prefill lane (full gather in both
        modes) is excluded by construction."""
        infos = [self.step_grid_info(s) for s in self.attn_len_samples]
        n = len(infos)
        total_live = sum(i["pages_live_total"] for i in infos)
        total_paged = sum(i["kv_bytes"] for i in infos)
        total_gather = sum(i["kv_bytes_gather"] for i in infos)
        # Per-chip bytes of THIS mode's policy (paged streams live
        # pages, gather reconstructs the full table): heads shard
        # exactly, so per-chip is 1/tp of the totals — the honest form
        # of the TP bandwidth claim (`serve_bench --ab-tp` pins
        # kv_bytes_per_chip <= unsharded/tp).
        total_chip = (total_paged if self.config.attention == "paged"
                      else total_gather) // self.tp
        return {
            "mode": self.config.attention,
            "decode_steps": n,
            "page_size": self.config.page_size,
            "pages_per_seq": self.cache.pages_per_seq,
            "pages_live_per_step_mean":
                round(total_live / n, 2) if n else None,
            "pages_full_per_step":
                self.config.decode_slots * self.cache.pages_per_seq,
            "kv_bytes_per_step_paged":
                round(total_paged / n, 1) if n else None,
            "kv_bytes_per_step_gather":
                total_gather // n if n else None,
            "kv_fetch_frac":
                round(total_paged / total_gather, 4) if n else None,
            "tp": self.tp,
            "kv_bytes_per_chip":
                round(total_chip / n, 1) if n else None,
        }
