"""Radix-tree prefix index over page-aligned token chunks — SGLang's
RadixAttention idea on this repo's paged KV cache.

A request's prompt is keyed as a chain of ``page_size``-token chunks;
each radix node owns the PHYSICAL page that chunk's K/V rows were
prefilled into. :meth:`PrefixIndex.match` maps a new prompt to the
longest chain of already-filled pages, admission maps those pages into
the new request's page table read-only (``allocator.retain`` — the
copy-on-write refcounting in ``kvcache.py``), and prefill starts at
the first miss. One cold prefill per unique prefix, every later
request pays only its tail.

Correctness ground rules (each one load-bearing):

* **Only FULL prompt pages are indexable or matchable** — a partial
  last page will still be written by its owner (and a matched page by
  nobody: new holders write from their first missed position onward),
  so indexed pages are write-free by construction; the engine's COW
  guard (``cow_page``) stays a defensive backstop, not the hot path.
* **A match never covers the whole prompt**: the last prompt token is
  always prefilled (``matched_tokens < prompt_len``), so the first
  generated token's logits come off the same prefill path as a cold
  request — the cache-hit stream is bit-identical to the cold one.
* **The index holds its own +1 refcount** on every entry's page, so a
  prefix outlives the request that prefilled it; under allocator
  pressure :meth:`reclaim` drops least-recently-touched leaves whose
  pages ONLY the index still holds (never a page any request maps),
  and entries invalidate on that release — a freed page can never be
  matched again.
* **A params version change flushes everything** (:meth:`flush`):
  K/V rows are a function of the weights, so stale-version pages must
  never serve a new-version request.

:func:`prefix_route_key` is the fleet-router side of the same idea: a
stable hash of the normalized (page-aligned, matchable) prefix that
rendezvous-ranks replicas, so requests sharing a prefix land on the
replica that already holds its pages — one cold prefill per unique
prefix per REPLICA instead of per request.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple


def aligned_prefix_len(prompt_len: int, page_size: int) -> int:
    """Tokens of ``prompt_len`` that are matchable: whole pages only,
    and never the entire prompt (the last token always prefills so
    first-token logits exist on the hit path)."""
    if prompt_len <= 1:
        return 0
    return ((prompt_len - 1) // page_size) * page_size


def prefix_route_key(prompt: Sequence[int],
                     page_size: int) -> Optional[str]:
    """Stable hex digest of the prompt's normalized prefix — the
    router's rendezvous-hash input. ``None`` when the prompt has no
    matchable prefix (no full page clear of the last token): such
    requests carry no affinity and route purely least-loaded.

    The key hashes the FIRST page-aligned chunk only, deliberately:

    * two requests sharing ANY matchable prefix necessarily share
      their first page, so first-chunk hashing co-locates every group
      that could ever share pages (hashing each request's own full
      aligned prefix would split "system prompt + user A" from
      "system prompt + user B" — the exact workload prefix caching
      exists for);
    * :func:`~horovod_tpu.serve.scheduler.rebase_for_recompute` only
      APPENDS tokens, so a redispatched request keeps its key — the
      drained requests of a dead replica all rendezvous onto the same
      survivor, where the first to arrive re-prefills the prefix once
      and the rest hit it.
    """
    n = aligned_prefix_len(len(prompt), page_size)
    if n <= 0:
        return None
    raw = ",".join(str(int(t)) for t in prompt[:page_size]).encode()
    return hashlib.sha256(raw).hexdigest()


def rendezvous_rank(route_key: str, replica_id: int) -> int:
    """Deterministic per-(prefix, replica) weight for highest-random-
    weight routing: every router instance — and every incarnation of
    the fleet — ranks the same replica first for the same prefix, with
    no shared state to migrate when replicas die (the next-ranked
    survivor simply becomes the prefix's home)."""
    h = hashlib.sha256(f"{route_key}:{replica_id}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class _Node:
    __slots__ = ("children", "page", "touch")

    def __init__(self, page: Optional[int] = None):
        #: chunk (tuple of page_size token ids) -> child node
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.page = page
        self.touch = 0


class PrefixIndex:
    """The radix index over one :class:`~horovod_tpu.serve.kvcache.
    PagedKVCache`'s allocator. Host-side bookkeeping only — pages
    themselves never move; the index just remembers which physical
    page holds which chunk's K/V rows and keeps them alive."""

    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self._root = _Node()
        self._clock = 0
        #: live entries (nodes holding a page)
        self.entries = 0
        # cumulative counters (reset via reset_metrics)
        self.lookups = 0
        self.hits = 0
        self.tokens_hit = 0
        self.pages_shared = 0
        self.inserts = 0
        self.reclaimed = 0
        self.flushes = 0

    # ------------------------------------------------------- matching

    def _chunks(self, prompt: Sequence[int], n_tokens: int):
        ps = self.page_size
        for i in range(n_tokens // ps):
            yield tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])

    def match(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest chain of already-filled pages for ``prompt``:
        returns ``(pages, matched_tokens)`` where ``pages[i]`` holds
        positions ``i*page_size..(i+1)*page_size-1``. The caller maps
        the pages (``retain``) into the request's table and starts
        prefill at ``matched_tokens``. Does NOT retain — admission
        does, so a match that loses an admission race leaks nothing.
        Counter-pure for the same reason (reserve-mode admission
        re-probes the waiting queue head every step): the admission
        that STICKS commits the counters via :meth:`note_admission`."""
        self._clock += 1
        node, pages = self._root, []
        matchable = aligned_prefix_len(len(prompt), self.page_size)
        for chunk in self._chunks(prompt, matchable):
            child = node.children.get(chunk)
            if child is None:
                break
            child.touch = self._clock
            pages.append(child.page)
            node = child
        return pages, len(pages) * self.page_size

    def note_admission(self, pages_hit: int, tokens_hit: int) -> None:
        """Commit the hit counters for ONE admitted request — so the
        hit rate is per request served, not per admission probe."""
        self.lookups += 1
        if pages_hit:
            self.hits += 1
            self.tokens_hit += tokens_hit
            self.pages_shared += pages_hit

    # ------------------------------------------------------ insertion

    def insert(self, prompt: Sequence[int],
               page_table: Sequence[int]) -> int:
        """Index a finished prefill: every FULL prompt page of
        ``prompt`` (whose K/V now sit in ``page_table``) becomes a
        radix entry, each newly-indexed page retained once (+1 — the
        index's own hold, so the prefix survives the request). Chunks
        already present keep their existing page (first prefill wins;
        identical weights ⇒ identical K/V, so either copy serves).
        Returns the number of NEW entries created."""
        self._clock += 1
        ps = self.page_size
        full = (len(prompt) // ps) * ps
        node, created = self._root, 0
        for i, chunk in enumerate(self._chunks(prompt, full)):
            child = node.children.get(chunk)
            if child is None:
                page = int(page_table[i])
                if page < 0:
                    break
                self.allocator.retain([page])
                child = _Node(page)
                node.children[chunk] = child
                self.entries += 1
                created += 1
            child.touch = self._clock
            node = child
        self.inserts += created
        return created

    # ------------------------------------------------------- eviction

    def reclaim(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping least-recently-
        touched LEAF entries whose pages only the index holds
        (refcount == 1 — releasing actually frees them; a page any
        request still maps is never a victim). Dropping leaves first
        keeps every surviving chain reachable. Returns pages freed."""
        freed = 0
        while freed < n_pages:
            victim = self._lru_reclaimable_leaf()
            if victim is None:
                break
            parent, chunk, child = victim
            self.allocator.release([child.page])
            del parent.children[chunk]
            self.entries -= 1
            self.reclaimed += 1
            freed += 1
        return freed

    def _lru_reclaimable_leaf(self):
        best = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for chunk, child in node.children.items():
                if child.children:
                    stack.append(child)
                elif self.allocator.refcount(child.page) == 1:
                    if best is None or child.touch < best[2].touch:
                        best = (node, chunk, child)
        return best

    def flush(self) -> int:
        """Drop EVERY entry, releasing the index's holds — the params-
        update path (stale-version K/V must never serve a new-version
        request). Pages still mapped by in-flight requests stay alive
        under their remaining refcounts. Returns entries dropped."""
        dropped = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                self.allocator.release([child.page])
                dropped += 1
                stack.append(child)
        self._root = _Node()
        self.entries = 0
        self.flushes += 1
        return dropped

    # ---------------------------------------------------------- stats

    def stats(self) -> Dict[str, int]:
        return {
            "entries": self.entries,
            "lookups": self.lookups,
            "hits": self.hits,
            "tokens_hit": self.tokens_hit,
            "pages_shared": self.pages_shared,
            "inserts": self.inserts,
            "reclaimed": self.reclaimed,
            "flushes": self.flushes,
        }

    def reset_metrics(self) -> None:
        """Zero the cumulative counters (entries stay — the measured
        window starts warm, like the engine's own reset)."""
        self.lookups = self.hits = self.tokens_hit = 0
        self.pages_shared = self.inserts = 0
        self.reclaimed = self.flushes = 0
