"""KV-page transfer lane: the chunk stream's second consumer.

The disaggregated prefill/decode handoff (``serve/disagg.py``) ships a
finished request's KV pages — :meth:`kvcache.PagedKVCache.export_pages
<horovod_tpu.serve.kvcache.PagedKVCache.export_pages>`'s deterministic
``HVKV`` blob — from a prefill replica to a decode replica under
EXACTLY the discipline PR 15 built for weights: a leading manifest
(whole-blob sha256, sizes), bounded chunks each carrying its offset and
its own crc32, contiguity-enforced assembly with resume-from-offset,
and a digest-verified commit (no partial import, ever). All of that
lives in :mod:`~horovod_tpu.serve.chunk_stream` — ONE framing
implementation, two consumers; this module adds only the KV lane's
specifics:

* the stream kind ``"hvsf-kv"`` (a KV receiver fed a params manifest —
  or the reverse — fails typed at the manifest, not at import);
* the request id riding in the manifest (``extra``), so a receiver can
  never commit one request's pages under another's table;
* :class:`KvSender` / :class:`KvReceiver`, the two ends the worker RPC
  verbs (``kv_export_*`` / ``kv_import_*``) and the inproc fleet both
  drive — the in-memory fleet runs the SAME chunk codec, so
  ``kv_bytes_shipped`` means the same thing on every transport.

Unlike the params push lane, a KV transfer is NEVER retried across a
TransportError: the death of either side mid-transfer drains the
request through the shipped router bookkeeping
(``rebase_for_recompute`` → requeue, at-most-once) — recomputing a
prefix is always correct, while a retried half-transfer would need
cross-replica transactional state the fleet deliberately does not
carry. Resume-from-offset exists IN the protocol (``begin`` returns
``have_bytes``) and covers the benign case: a re-begin of the same
(rid, digest) payload after a torn chunk, on a still-healthy pair.

Stdlib-only, like the framing itself.
"""

from __future__ import annotations

from typing import Dict

from horovod_tpu.serve.chunk_stream import (
    DEFAULT_CHUNK_BYTES,
    BufferAssembler,
    make_chunk,
    make_manifest,
)
from horovod_tpu.serve.transport import FrameError

#: Stream kind pinning the KV lane apart from ``"hvsf-params"``.
KV_KIND = "hvsf-kv"

#: KV transfer protocol version (the chunk framing's version-mix check
#: runs per transfer; KV payloads are transient, so unlike weights
#: there is no artifact versioning to thread through).
KV_WIRE_VERSION = 1


def make_kv_manifest(blob: bytes, *, rid: int,
                     chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Dict:
    """Manifest for one request's KV-page blob: shared framing fields
    plus the request id (the receiver pins chunks AND commit to it)."""
    return make_manifest(blob, kind=KV_KIND, version=KV_WIRE_VERSION,
                         chunk_bytes=chunk_bytes,
                         extra={"rid": int(rid)})


class KvSender:
    """Prefill-side half of one KV transfer: holds the exported blob
    (re-exportable bit-identically, so re-creating a sender after a
    torn transfer resumes the same payload) and frames chunks on
    demand. Pure host state — dropping a sender aborts nothing on the
    wire."""

    def __init__(self, blob: bytes, rid: int,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        self.blob = blob
        self.rid = int(rid)
        self.manifest = make_kv_manifest(blob, rid=rid,
                                         chunk_bytes=chunk_bytes)

    @property
    def num_chunks(self) -> int:
        return int(self.manifest["num_chunks"])

    @property
    def total_bytes(self) -> int:
        return int(self.manifest["total_bytes"])

    def chunk(self, index: int) -> Dict:
        return make_chunk(self.blob, self.manifest, index)


class KvReceiver:
    """Decode-side half: a :class:`~horovod_tpu.serve.chunk_stream.
    BufferAssembler` pinned to one request id. ``begin`` returns the
    resume offset; ``commit`` digest-verifies and hands the blob out
    exactly once — the caller imports it under the engine lock and only
    then acks, so a commit the prefill side never hears about leaves
    the pages parked there (at-most-once comes from the router's
    ownership move, not from this class)."""

    def __init__(self, rid: int):
        self.rid = int(rid)
        self._asm = BufferAssembler(kind=KV_KIND)

    @property
    def have_bytes(self) -> int:
        return self._asm.have_bytes

    def begin(self, manifest: Dict) -> int:
        if int(manifest.get("rid", -1)) != self.rid:
            raise FrameError(
                f"kv manifest is for rid {manifest.get('rid')!r}, this "
                f"receiver is armed for rid {self.rid} — one request's "
                "pages must never land under another's table")
        return self._asm.begin(manifest)

    def write_chunk(self, chunk: Dict) -> int:
        return self._asm.write_chunk(chunk)

    def commit(self) -> bytes:
        blob, _sha = self._asm.commit()
        return blob

    def abort(self) -> None:
        self._asm.abort()


__all__ = ["KV_KIND", "KV_WIRE_VERSION", "KvReceiver", "KvSender",
           "make_kv_manifest"]
