"""Serving SLO metrics: TTFT, per-token latency, throughput, occupancy.

Definitions (the ones docs/serving.md's runbook tunes against):

* **TTFT** — time-to-first-token: ``t_first_token - arrival``. Includes
  queueing delay (open-loop honesty: a saturated engine shows it in
  TTFT, not by silently back-pressuring the generator).
* **per-token latency (TBT)** — inter-token gaps within one request:
  ``token_times[i] - token_times[i-1]`` (the first gap is measured
  from the first token). What a streaming client perceives per token.
* **tokens/s/chip** — total generated tokens / wall / chips. Generated
  only; prompt tokens are the cost of TTFT, not serving throughput.
* **occupancy** — fraction of allocatable KV pages in use, sampled
  once per engine step; mean and max over the run.

Percentiles use the nearest-rank method on the sorted sample (p50/p99
of an empty sample render as None) — no interpolation, so a reported
p99 is always a latency some real request paid.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence


def percentile(xs: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile (p in [0, 100]); None on empty input."""
    if not xs:
        return None
    s = sorted(xs)
    rank = max(1, math.ceil(p / 100.0 * len(s)))
    return s[min(rank, len(s)) - 1]


def _r(x: Optional[float], nd: int = 2) -> Optional[float]:
    return None if x is None else round(x, nd)


def summarize(requests, wall_s: float, chips: int = 1,
              occupancy_samples: Optional[List[float]] = None) -> Dict:
    """Aggregate a run into the bench-record stats dict.

    ``requests`` is any iterable of :class:`~horovod_tpu.serve.
    scheduler.Request` (finished or not — unfinished ones count toward
    states but contribute only the latency samples they already
    earned)."""
    reqs = list(requests)
    ttft_ms, tbt_ms = [], []
    tokens = 0
    states: Dict[str, int] = {}
    for r in reqs:
        states[r.state] = states.get(r.state, 0) + 1
        tokens += len(r.output)
        if r.t_first_token is not None:
            ttft_ms.append((r.t_first_token - r.arrival) * 1e3)
        prev = r.t_first_token
        for t in r.token_times:
            if prev is not None and t > prev:
                tbt_ms.append((t - prev) * 1e3)
            prev = t
    occ = occupancy_samples or []
    return {
        "requests": len(reqs),
        "by_state": states,
        "generated_tokens": tokens,
        "tokens_per_sec_per_chip":
            _r(tokens / wall_s / max(1, chips), 1) if wall_s > 0 else None,
        "ttft_ms": {"p50": _r(percentile(ttft_ms, 50)),
                    "p99": _r(percentile(ttft_ms, 99)),
                    "mean": _r(sum(ttft_ms) / len(ttft_ms))
                            if ttft_ms else None},
        "tbt_ms": {"p50": _r(percentile(tbt_ms, 50)),
                   "p99": _r(percentile(tbt_ms, 99))},
        "pages": {"occupancy_mean": _r(sum(occ) / len(occ), 4)
                              if occ else None,
                  "occupancy_max": _r(max(occ), 4) if occ else None},
    }
