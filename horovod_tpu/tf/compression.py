"""Gradient compression for the TF binding (parity surface of reference
horovod/tensorflow/compression.py:24-60: a Compressor interface with
``none`` and ``fp16`` implementations; decompress restores the original
dtype)."""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    """Interface for compressing/decompressing a tensor around the wire
    (reference compression.py:24-38)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Ride the ring at half precision; restore the caller's dtype after
    (reference compression.py:46-60)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype != tf.float16:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tf.cast(tensor, ctx)


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}``."""

    none = NoneCompressor
    fp16 = FP16Compressor
