"""horovod_tpu.tf — TensorFlow (CPU eager) binding over the native core.

Parity surface of the reference's largest binding
(horovod/tensorflow/__init__.py:151-326 + tensorflow/mpi_ops.py), rebuilt
sessionless: TF2 eager tensors view as numpy buffers and ride the same
authenticated TCP star/ring native core (csrc/) as the torch binding —
there is no per-(dtype x op) TF custom-op library to compile (reference
tensorflow/mpi_ops.cc:276-463). Graph-mode sessions are gone from modern
TF; ``tf.function`` users call these ops eagerly around their compiled
step, and TF-on-TPU traffic belongs to the jax lane (the declared
flagship, README "Scope decisions").

Surface: init/rank/size family, differentiable allreduce / allgather /
broadcast (gradient registrations mirror reference
tensorflow/mpi_ops.py:94-183), ``DistributedGradientTape``
(reference tensorflow/__init__.py:151-244), ``broadcast_variables``,
and tf.keras callbacks in :mod:`horovod_tpu.tf.keras`
(reference keras/callbacks.py).
"""

from __future__ import annotations

import re
import threading
from typing import Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common.basics import check_extension
from horovod_tpu.common.launcher_env import native_init_kwargs
from horovod_tpu.native import NativeCore
from horovod_tpu.tf.compression import Compression

_core: Optional[NativeCore] = None
_name_regex = re.compile(r"[^a-zA-Z0-9_.]")
_name_lock = threading.Lock()
_name_counter = 0


def init(comm=None) -> None:
    """Initialize from launcher env vars (same contract as the torch
    binding, torch/__init__.py; reference tensorflow/__init__.py
    delegated to the common C init). ``comm`` forms a sub-communicator
    via the collective world rendezvous (docs/native-core.md)."""
    global _core
    if _core is not None and _core.initialized:
        return
    core = NativeCore()
    core.init(comm=comm, **native_init_kwargs())
    _core = core


def shutdown() -> None:
    global _core
    if _core is not None:
        _core.shutdown()
        _core = None


def _require_core() -> NativeCore:
    if _core is None:
        raise RuntimeError(
            "horovod_tpu.tf has not been initialized; call hvd.init().")
    return _core


def rank() -> int:
    return _require_core().rank()


def size() -> int:
    return _require_core().size()


def local_rank() -> int:
    return _require_core().local_rank()


def local_size() -> int:
    return _require_core().local_size()


def mpi_threads_supported() -> bool:
    """No MPI anywhere in this framework (parity shim, reference
    operations.cc:2462-2468)."""
    _require_core()
    return False


def _next_name(op: str, name: Optional[str]) -> str:
    global _name_counter
    if name is not None:
        return _name_regex.sub("_", name)
    with _name_lock:
        _name_counter += 1
        return f"{op}.noname.{_name_counter}"


def _to_writable_numpy(tensor: tf.Tensor) -> np.ndarray:
    """A contiguous, writable numpy buffer of the tensor's value (the
    native core reduces through raw pointers in place). EagerTensor
    .numpy() may return a read-only view, so always copy."""
    return np.array(tensor.numpy())


def _run_inplace(op: str, name: Optional[str], tensor: tf.Tensor,
                 *args) -> np.ndarray:
    core = _require_core()
    arr = _to_writable_numpy(tensor)
    h = getattr(core, op)(_next_name(op.split("_")[0], name), arr, *args)
    core.wait(h)
    core.release(h)
    return arr


# ------------------------------------------------------------- collectives


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none):
    """Differentiable eager allreduce; gradient = allreduce, the
    transpose of a sum over ranks (reference tensorflow/mpi_ops.py:
    94-121 registered the same gradient for graph mode).

    A ``tf.IndexedSlices`` input (sparse gradient, e.g. from an
    embedding lookup) takes the reference's sparse path
    (tensorflow/__init__.py:96-110): allgather the slices' values and
    indices instead of densifying — summing duplicate indices is the
    consumer's contract, exactly as with local IndexedSlices."""
    if isinstance(tensor, tf.IndexedSlices):
        if average and not tensor.values.dtype.is_floating:
            raise ValueError(
                f"allreduce with average=True is not supported for integer "
                f"IndexedSlices values dtype {tensor.values.dtype}; pass "
                f"average=False (sum) or cast to a floating dtype first.")
        values = allgather(tensor.values, name=f"{name}.values"
                           if name else None)
        if average:
            values = values / size()
        indices = allgather(tensor.indices, name=f"{name}.indices"
                            if name else None)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    tensor = tf.convert_to_tensor(tensor)
    if average and not tensor.dtype.is_floating:
        raise ValueError(
            f"allreduce with average=True is not supported for integer "
            f"tensor dtype {tensor.dtype}; pass average=False (sum) or "
            f"cast to a floating dtype first.")

    @tf.custom_gradient
    def _allreduce(x):
        compressed, ctx = compression.compress(x)
        arr = _run_inplace("allreduce_async_", name, compressed)
        out = compression.decompress(tf.constant(arr), ctx)
        if average:
            out = out / size()

        def grad(dy):
            return allreduce(dy, average=average, compression=compression)

        return out, grad

    return _allreduce(tensor)


def allgather(tensor, name: Optional[str] = None):
    """Differentiable eager allgather: concatenation along dim 0 across
    ranks, ragged first dims allowed; gradient = allreduce-sum then this
    rank's row slice (reference tensorflow/mpi_ops.py:127-148)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _allgather(x):
        core = _require_core()
        arr = np.ascontiguousarray(x.numpy())
        h = core.allgather_async(_next_name("allgather", name), arr)
        core.wait(h)
        out_np = core.take_result(h, arr.dtype, tuple(arr.shape[1:]))
        my_rows = arr.shape[0] if arr.ndim else 1

        def grad(dy):
            rows = _require_core().allgather_async(
                _next_name("allgather", None),
                np.array([my_rows], np.int64))
            _require_core().wait(rows)
            all_rows = _require_core().take_result(rows, np.int64, ())
            offset = int(all_rows[:rank()].sum())
            summed = allreduce(dy, average=False)
            return summed[offset:offset + my_rows]

        return tf.constant(out_np), grad

    return _allgather(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Differentiable eager broadcast; gradient = allreduce-sum on the
    root, zeros elsewhere (reference tensorflow/mpi_ops.py:168-183)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _broadcast(x):
        arr = _run_inplace("broadcast_async_", name, x, root_rank)

        def grad(dy):
            summed = allreduce(dy, average=False)
            if rank() != root_rank:
                summed = tf.zeros_like(summed)
            return summed

        return tf.constant(arr), grad

    return _broadcast(tensor)


# ---------------------------------------------------- variables + gradients


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable its root-rank value (the sessionless form of
    the reference's broadcast_global_variables op,
    tensorflow/__init__.py:246-261)."""
    for i, var in enumerate(variables):
        var.assign(broadcast(var, root_rank,
                             name=f"broadcast.var.{i}.{var.name}"))


class DistributedGradientTape:
    """Wraps ``tf.GradientTape`` so ``.gradient()`` returns
    rank-averaged gradients (reference tensorflow/__init__.py:151-244;
    the eager path allreduces at gradient-retrieval time, which is the
    reference's _make_allreduce_grads_fn applied eagerly). All other
    attributes delegate to the wrapped tape, so ``with tf.GradientTape()
    as tape: ... hvd.DistributedGradientTape(tape).gradient(...)`` is a
    one-line migration."""

    def __init__(self, gradtape: tf.GradientTape,
                 compression=Compression.none, average: bool = True,
                 sparse_as_dense: bool = False):
        self._tape = gradtape
        self._compression = compression
        self._average = average
        self._sparse_as_dense = sparse_as_dense

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        flat = tf.nest.flatten(grads)
        reduced = _allreduce_batch(flat, self._average, self._compression,
                                   sparse_as_dense=self._sparse_as_dense)
        return tf.nest.pack_sequence_as(grads, reduced)


def _allreduce_batch(tensors, average, compression,
                     sparse_as_dense: bool = False):
    """Enqueue EVERY tensor's allreduce before waiting on any, so the
    native core's fusion buffer packs small gradients into one ring pass
    (the same reason the torch DistributedOptimizer enqueues from hooks
    and drains in synchronize(); one-at-a-time sync calls would serialize
    N ring latencies and defeat HOROVOD_FUSION_THRESHOLD). Entries may be
    None (unconnected gradients), preserved as None. ``tf.IndexedSlices``
    entries ride the sparse allgather path (or densify first under
    ``sparse_as_dense`` — reference DistributedOptimizer's flag,
    tensorflow/__init__.py:64-66); they resolve inline since the gather
    has its own wire."""
    core = _require_core()
    entries = []
    for i, t in enumerate(tensors):
        if t is None:
            entries.append(None)
            continue
        if isinstance(t, tf.IndexedSlices):
            if sparse_as_dense:
                t = tf.convert_to_tensor(t)
            else:
                # Async like the dense entries: both allgathers enqueue
                # now and drain in the second loop, keeping the batch's
                # enqueue-everything-then-wait property.
                vals = np.ascontiguousarray(t.values.numpy())
                idxs = np.ascontiguousarray(t.indices.numpy())
                hv = core.allgather_async(
                    _next_name("allgather", f"grad.{i}.values"), vals)
                hi = core.allgather_async(
                    _next_name("allgather", f"grad.{i}.indices"), idxs)
                entries.append(("sparse", hv, hi, vals, idxs, t))
                continue
        compressed, ctx = compression.compress(tf.convert_to_tensor(t))
        arr = _to_writable_numpy(compressed)
        # Async enqueue per gradient INTO the native core, whose
        # background cycle fuses same-dtype responses into flat buckets
        # (csrc negotiation) — the per-tensor loop is the enqueue API,
        # not the wire shape, so HVD006's fusion advice already holds.
        h = core.allreduce_async_(  # hvdlint: disable=HVD006
            _next_name("allreduce", f"grad.{i}"), arr)
        entries.append((h, arr, ctx))
    out = []
    for entry in entries:
        if entry is None:
            out.append(None)
            continue
        if isinstance(entry, tuple) and entry[0] == "sparse":
            _, hv, hi, vals, idxs, t = entry
            core.wait(hv)
            gvals = tf.constant(core.take_result(
                hv, vals.dtype, tuple(vals.shape[1:])))
            core.wait(hi)
            gidxs = tf.constant(core.take_result(
                hi, idxs.dtype, tuple(idxs.shape[1:])))
            if average:
                gvals = gvals / size()
            out.append(tf.IndexedSlices(gvals, gidxs,
                                        dense_shape=t.dense_shape))
            continue
        h, arr, ctx = entry
        core.wait(h)
        core.release(h)
        res = compression.decompress(tf.constant(arr), ctx)
        out.append(res / size() if average else res)
    return out


__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "mpi_threads_supported", "check_extension",
    "allreduce", "allgather", "broadcast", "broadcast_variables",
    "DistributedGradientTape", "Compression",
]
