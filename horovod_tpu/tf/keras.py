"""tf.keras surface for the TF binding (parity of reference
horovod/keras/__init__.py + callbacks.py: DistributedOptimizer,
BroadcastGlobalVariablesCallback, MetricAverageCallback; the LR-schedule
callbacks live on the flax lane, horovod_tpu/flax/callbacks.py, which is
the flagship's keras analogue)."""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu.tf as hvd
from horovod_tpu.tf import Compression, _allreduce_batch


def DistributedOptimizer(optimizer, compression=Compression.none,
                         average: bool = True):
    """Make a tf.keras optimizer average gradients over ranks before
    applying them (reference keras/__init__.py:32-52 wrapped
    get_gradients; modern keras routes every path — fit(), custom
    loops — through apply_gradients, so that is the interception
    point). The instance is re-classed in place, torch-binding style,
    so isinstance, serialization, and existing references keep working;
    the batched allreduce keeps the native core's fusion engaged."""
    base = optimizer.__class__

    def _reduce(grads):
        if tf.executing_eagerly():
            return _allreduce_batch(grads, average, compression)
        # Inside fit()'s compiled train step the gradients are symbolic;
        # tf.py_function hops back to eager for the native-core
        # collectives — one graph node per step, so every rank issues
        # the batch in the same deterministic order. IndexedSlices
        # (embedding gradients) densify here: py_function transports
        # dense tensors only — the behavior of the reference's
        # sparse_as_dense flag, applied where the transport demands it.
        grads = [tf.convert_to_tensor(g)
                 if isinstance(g, tf.IndexedSlices) else g for g in grads]
        present = [g for g in grads if g is not None]
        outs = tf.py_function(
            lambda *ts: _allreduce_batch(list(ts), average, compression),
            inp=present, Tout=[g.dtype for g in present])
        outs = [outs] if not isinstance(outs, (list, tuple)) else list(outs)
        it = iter(outs)
        reduced = []
        for g in grads:
            if g is None:
                reduced.append(None)
            else:
                out = next(it)
                out.set_shape(g.shape)
                reduced.append(out)
        return reduced

    if hasattr(base, "apply"):
        # Keras 3: apply_gradients is a thin wrapper over apply(), and
        # custom loops (and LossScaleOptimizer's inner calls) invoke
        # apply() directly — intercepting the funnel point covers every
        # path with no double-reduce (the base apply_gradients delegates
        # into this override).
        def apply(self, grads, trainable_variables=None, **kwargs):
            reduced = _reduce(list(grads))
            return super(cls, self).apply(reduced, trainable_variables,
                                          **kwargs)

        cls = type(base.__name__, (base,), {"apply": apply})
    else:  # pre-Keras-3 optimizers: apply_gradients IS the funnel
        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = _reduce([g for g, _ in gv])
            return super(cls, self).apply_gradients(
                [(rg, v) for rg, (_, v) in zip(reduced, gv)],
                *args, **kwargs)

        cls = type(base.__name__, (base,),
                   {"apply_gradients": apply_gradients})
    optimizer.__class__ = cls
    return optimizer


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` so
    randomly-initialized or checkpoint-restored workers agree before
    averaged training proceeds. Broadcasts at train begin when the model
    is already built; a lazily-built model (no input_shape, subclassed)
    has NO variables yet at that point, so the broadcast defers to the
    end of the first batch — the reference ran on_batch_end(batch 0) for
    exactly this reason (reference keras/callbacks.py:24-45), accepting
    one rank-local step that the full state broadcast then overwrites."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def _broadcast(self) -> None:
        hvd.broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None) is not None:
            opt_vars = (opt.variables() if callable(opt.variables)
                        else opt.variables)
            if opt_vars:
                hvd.broadcast_variables(opt_vars, self.root_rank)
        self._done = True

    def on_train_begin(self, logs=None):
        if not self._done and self.model.variables:
            self._broadcast()

    def on_train_batch_end(self, batch, logs=None):
        if not self._done:
            self._broadcast()


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch-end metrics over ranks so every worker logs (and
    checkpoints/early-stops on) the global value, not its shard's
    (reference keras/callbacks.py:48-86)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for key, value in list(logs.items()):
                logs[key] = float(hvd.allreduce(
                    tf.constant(np.float64(value)), average=True,
                    name=f"metric.{key}"))
