"""tf.keras callbacks for the TF binding (parity surface of reference
horovod/keras/callbacks.py: BroadcastGlobalVariablesCallback and
MetricAverageCallback; the LR-schedule callbacks live on the flax lane,
horovod_tpu/flax/callbacks.py, which is the flagship's keras analogue)."""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu.tf as hvd


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` so
    randomly-initialized or checkpoint-restored workers agree before
    averaged training proceeds. Broadcasts at train begin when the model
    is already built; a lazily-built model (no input_shape, subclassed)
    has NO variables yet at that point, so the broadcast defers to the
    end of the first batch — the reference ran on_batch_end(batch 0) for
    exactly this reason (reference keras/callbacks.py:24-45), accepting
    one rank-local step that the full state broadcast then overwrites."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def _broadcast(self) -> None:
        hvd.broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None) is not None:
            opt_vars = (opt.variables() if callable(opt.variables)
                        else opt.variables)
            if opt_vars:
                hvd.broadcast_variables(opt_vars, self.root_rank)
        self._done = True

    def on_train_begin(self, logs=None):
        if not self._done and self.model.variables:
            self._broadcast()

    def on_train_batch_end(self, batch, logs=None):
        if not self._done:
            self._broadcast()


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch-end metrics over ranks so every worker logs (and
    checkpoints/early-stops on) the global value, not its shard's
    (reference keras/callbacks.py:48-86)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for key, value in list(logs.items()):
                logs[key] = float(hvd.allreduce(
                    tf.constant(np.float64(value)), average=True,
                    name=f"metric.{key}"))
