"""tf.keras surface for the TF binding (parity of reference
horovod/keras/__init__.py + callbacks.py + _keras/callbacks.py:
DistributedOptimizer, BroadcastGlobalVariablesCallback,
MetricAverageCallback, LearningRateScheduleCallback,
LearningRateWarmupCallback, load_model; the flax lane
(horovod_tpu/flax/callbacks.py) carries the same surface for the
flagship jax path)."""

from __future__ import annotations

import warnings

import numpy as np
import tensorflow as tf

import horovod_tpu.tf as hvd
from horovod_tpu.tf import Compression, _allreduce_batch


def _distributed_class(base, compression=Compression.none,
                       average: bool = True):
    """Build the rank-averaging subclass of optimizer class ``base``
    (shared by DistributedOptimizer's in-place re-class and
    load_model's post-load re-wrap). The subclass keeps ``base``'s
    __name__ so keras serialization round-trips."""

    def _reduce(grads):
        if tf.executing_eagerly():
            return _allreduce_batch(grads, average, compression)
        # Inside fit()'s compiled train step the gradients are symbolic;
        # tf.py_function hops back to eager for the native-core
        # collectives — one graph node per step, so every rank issues
        # the batch in the same deterministic order. IndexedSlices
        # (embedding gradients) densify here: py_function transports
        # dense tensors only — the behavior of the reference's
        # sparse_as_dense flag, applied where the transport demands it.
        grads = [tf.convert_to_tensor(g)
                 if isinstance(g, tf.IndexedSlices) else g for g in grads]
        present = [g for g in grads if g is not None]
        outs = tf.py_function(
            lambda *ts: _allreduce_batch(list(ts), average, compression),
            inp=present, Tout=[g.dtype for g in present])
        outs = [outs] if not isinstance(outs, (list, tuple)) else list(outs)
        it = iter(outs)
        reduced = []
        for g in grads:
            if g is None:
                reduced.append(None)
            else:
                out = next(it)
                out.set_shape(g.shape)
                reduced.append(out)
        return reduced

    if hasattr(base, "apply"):
        # Keras 3: apply_gradients is a thin wrapper over apply(), and
        # custom loops (and LossScaleOptimizer's inner calls) invoke
        # apply() directly — intercepting the funnel point covers every
        # path with no double-reduce (the base apply_gradients delegates
        # into this override).
        def apply(self, grads, trainable_variables=None, **kwargs):
            reduced = _reduce(list(grads))
            return super(cls, self).apply(reduced, trainable_variables,
                                          **kwargs)

        # __module__ = base's: the subclass serializes as the plain
        # class (keras resolves module.class_name at load), so saved
        # models stay loadable by plain keras; load_model re-wraps.
        cls = type(base.__name__, (base,),
                   {"apply": apply, "__module__": base.__module__})
    else:  # pre-Keras-3 optimizers: apply_gradients IS the funnel
        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = _reduce([g for g, _ in gv])
            return super(cls, self).apply_gradients(
                [(rg, v) for rg, (_, v) in zip(reduced, gv)],
                *args, **kwargs)

        cls = type(base.__name__, (base,),
                   {"apply_gradients": apply_gradients,
                    "__module__": base.__module__})
    cls._hvd_distributed = True
    cls._hvd_wrap_args = (compression, average)
    return cls


def DistributedOptimizer(optimizer, compression=Compression.none,
                         average: bool = True):
    """Make a tf.keras optimizer average gradients over ranks before
    applying them (reference keras/__init__.py:32-52 wrapped
    get_gradients; modern keras routes every path — fit(), custom
    loops — through apply_gradients, so that is the interception
    point). The instance is re-classed in place, torch-binding style,
    so isinstance, serialization, and existing references keep working;
    the batched allreduce keeps the native core's fusion engaged.

    Performance caveat (graph mode): inside ``fit()``'s compiled train
    step the collectives route through one ``tf.py_function`` hop back
    to eager per step — correct and deterministic, but it pins a
    host-side transition on the step's critical path and densifies
    IndexedSlices (embedding gradients) for transport. This lane is the
    CPU/process-parallel binding; throughput-critical TPU training
    belongs on the flagship jax/flax lane, whose collectives compile
    into the XLA program itself.
    Re-wrapping an already-distributed optimizer is a no-op when the
    settings match and re-classes from the original base when they
    differ (e.g. load_model's default wrap followed by an explicit
    compression choice) — stacking two reduce layers would average
    twice."""
    cls = optimizer.__class__
    if getattr(cls, "_hvd_distributed", False):
        if cls._hvd_wrap_args == (compression, average):
            return optimizer
        cls = cls.__mro__[1]  # original base: swap, don't stack
    optimizer.__class__ = _distributed_class(cls, compression, average)
    return optimizer


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` so
    randomly-initialized or checkpoint-restored workers agree before
    averaged training proceeds. Broadcasts at train begin when the model
    is already built; a lazily-built model (no input_shape, subclassed)
    has NO variables yet at that point, so the broadcast defers to the
    end of the first batch — the reference ran on_batch_end(batch 0) for
    exactly this reason (reference keras/callbacks.py:24-45), accepting
    one rank-local step that the full state broadcast then overwrites."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def _broadcast(self) -> None:
        hvd.broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None) is not None:
            opt_vars = (opt.variables() if callable(opt.variables)
                        else opt.variables)
            if opt_vars:
                hvd.broadcast_variables(opt_vars, self.root_rank)
        self._done = True

    def on_train_begin(self, logs=None):
        if not self._done and self.model.variables:
            self._broadcast()

    def on_train_batch_end(self, batch, logs=None):
        if not self._done:
            self._broadcast()


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch-end metrics over ranks so every worker logs (and
    checkpoints/early-stops on) the global value, not its shard's
    (reference keras/callbacks.py:48-86)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for key, value in list(logs.items()):
                # Per-metric scalars once per epoch, each needing its own
                # negotiation/timeline name — not the per-gradient
                # anti-pattern HVD006 targets (see flax/callbacks.py).
                logs[key] = float(hvd.allreduce(  # hvdlint: disable=HVD006
                    tf.constant(np.float64(value)), average=True,
                    name=f"metric.{key}"))


def _get_value(ref):
    """Read a keras-3 Variable / tf.Variable / plain float uniformly."""
    if hasattr(ref, "numpy"):
        return float(ref.numpy())
    return float(ref)


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Scale the learning rate by ``multiplier(epoch)`` inside
    ``[start_epoch, end_epoch)`` (reference
    _keras/callbacks.py:131-203). ``staircase=True`` applies an
    integer-epoch multiplier on each epoch's first batch;
    ``staircase=False`` applies a fractional-epoch multiplier
    ``epoch + batch/steps_per_epoch`` on every batch.
    ``steps_per_epoch`` is autodetected from fit()'s params when
    possible.

    Momentum correction (reference _keras/callbacks.py:168-177:
    temporarily scale SGD momentum by ``new_lr/old_lr`` for the
    adjusted batch) is applied when the optimizer's ``momentum`` is an
    assignable variable. Keras 3 stores SGD momentum as a Python float
    that fit()'s compiled train step captures at trace time, so the
    correction is skipped there with a one-time warning — silently
    mutating the attribute would look applied while the compiled step
    kept the stale constant."""

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.current_epoch = None
        self._restore_momentum = None
        self._warned_momentum = False
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    # -- hyperparameter plumbing (keras-3 Variables / legacy Variables) --

    def _lr_ref(self):
        opt = self.model.optimizer
        ref = getattr(opt, "learning_rate", None)
        if ref is None:
            ref = getattr(opt, "lr", None)
        if ref is None or not hasattr(ref, "assign"):
            raise ValueError(
                "optimizer has no assignable learning_rate variable "
                "(LearningRateSchedule objects cannot be overridden by "
                "this callback)")
        return ref

    def _adjust(self, epoch) -> None:
        lr = self._lr_ref()
        old_lr = _get_value(lr)
        new_lr = self.initial_lr * self.multiplier(epoch)
        lr.assign(new_lr)
        if not self.momentum_correction or old_lr == 0.0:
            return
        mom = getattr(self.model.optimizer, "momentum", None)
        if hasattr(mom, "assign"):
            self._restore_momentum = _get_value(mom)
            mom.assign(self._restore_momentum * new_lr / old_lr)
        elif isinstance(mom, float) and mom and not self._warned_momentum:
            self._warned_momentum = True
            warnings.warn(
                "momentum correction skipped: this optimizer stores "
                "momentum as a Python constant that the compiled train "
                "step captured at trace time (keras 3 SGD); pass "
                "momentum_correction=False to silence", RuntimeWarning)

    def _restore(self) -> None:
        if self._restore_momentum is not None:
            self.model.optimizer.momentum.assign(self._restore_momentum)
            self._restore_momentum = None

    # -- keras hooks --

    def on_train_begin(self, logs=None):
        self.initial_lr = _get_value(self._lr_ref())
        if not self.staircase and not self.steps_per_epoch:
            steps = (self.params or {}).get("steps")
            if not steps:
                raise ValueError(
                    "could not autodetect steps_per_epoch; pass "
                    "steps_per_epoch= explicitly")
            self.steps_per_epoch = steps

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust(self.current_epoch)
        elif not self.staircase:
            self._adjust(self.current_epoch +
                         float(batch) / self.steps_per_epoch)

    def on_train_batch_end(self, batch, logs=None):
        self._restore()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_value(self._lr_ref())


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup over ``warmup_epochs`` from ``initial_lr /
    size`` to ``initial_lr`` (Goyal et al.; reference
    _keras/callbacks.py:206-229): compile the model with the full
    size-scaled rate, and this ramps the first epochs smoothly so
    large effective batches don't diverge at step 0."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # Nudge so the ramp lands exactly on 1.0 at the last batch
            # of the final warmup epoch (reference keeps TensorBoard
            # curves round the same way).
            epoch += 1.0 / self.steps_per_epoch
            return (1.0 / hvd.size() *
                    (epoch * (hvd.size() - 1) / warmup_epochs + 1))

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {_get_value(self._lr_ref()):g}.")


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none, average: bool = True):
    """Load a keras model with its optimizer wrapped as a
    DistributedOptimizer. The reference substituted wrapped optimizer
    classes into ``custom_objects`` at deserialization time
    (_keras/__init__.py:93-109); keras 3 resolves built-in classes by
    registered name before consulting ``custom_objects``
    (serialization_lib._retrieve_class_or_fn), so class substitution
    can no longer intercept them — instead the model loads normally
    (slot variables, lr, iteration count all restored) and the loaded
    optimizer instance is re-classed in place, which preserves that
    state exactly. ``custom_optimizers`` / ``custom_objects`` are
    still merged into the load so user-defined classes resolve."""
    objects = {}
    if custom_optimizers is not None:
        objects.update({cls.__name__: cls for cls in custom_optimizers})
    if custom_objects is not None:
        objects.update(custom_objects)
    model = tf.keras.models.load_model(filepath, custom_objects=objects)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        DistributedOptimizer(opt, compression=compression, average=average)
    return model
