"""Launcher-environment contract, shared by the native-core bindings.

The launcher (``horovod_tpu.run``) replaces the reference's
mpirun-provided MPI_COMM_WORLD with env vars (reference
operations.cc:1748-1797 derived the same values from MPI); both the
torch and tf bindings bootstrap their NativeCore from this one parser so
the contract cannot drift between them."""

from __future__ import annotations

import os


def native_init_kwargs() -> dict:
    """Keyword arguments for :meth:`NativeCore.init` from the launcher
    env. Single-process (no launcher) degenerates to size 1, the
    reference's "no cluster needed" mode (SURVEY §4 mechanism 1)."""
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    size = int(os.environ.get("HOROVOD_SIZE", "1"))
    controller = os.environ.get("HOROVOD_CONTROLLER", "127.0.0.1:29400")
    host, _, port = controller.rpartition(":")
    return dict(
        rank=rank,
        size=size,
        local_rank=int(os.environ.get("HOROVOD_LOCAL_RANK", str(rank))),
        local_size=int(os.environ.get("HOROVOD_LOCAL_SIZE", str(size))),
        coord_host=host or "127.0.0.1",
        coord_port=int(port),
        timeout_ms=int(os.environ.get("HOROVOD_START_TIMEOUT", "60")) * 1000,
    )
