"""Environment-variable configuration surface.

Keeps the reference's env-var names verbatim so scripts written against the
reference keep working (reference: horovod/common/operations.h:56-66 and the
parsing block horovod/common/operations.cc:1707-1909).

All values are read lazily at ``hvd.init()`` time into a :class:`Config`
snapshot, so tests can monkeypatch ``os.environ`` before init.
"""

from __future__ import annotations

import dataclasses
import os

# Reference defaults: 64 MB fusion threshold, 5 ms cycle time
# (horovod/common/operations.cc:1846, operations.h:56-60).
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 5.0
# Overlap-shaped gradient collectives (horovod_tpu/jax/fusion.py): buckets
# at or above this size take the reduce-scatter -> sharded-update ->
# all-gather form (same wire bytes as one allreduce — rs+ag IS the ring
# decomposition — but two independently schedulable halves XLA's async
# collective pass can slide under backward compute). 4 MiB: below it the
# per-collective latency of two ops beats the scheduling freedom.
DEFAULT_OVERLAP_SCATTER_THRESHOLD = 4 * 1024 * 1024
# HOROVOD_OVERLAP values (see horovod_tpu.jax.fusion.resolve_overlap).
OVERLAP_MODES = ("auto", "on", "off")
# HOROVOD_HIERARCHICAL values (horovod_tpu.jax.fusion.
# resolve_hierarchical): run each fused bucket as the two-level
# intra-slice reduce-scatter -> inter-slice exchange -> intra-slice
# all-gather ladder instead of one flat psum. "auto" (default) engages
# only when the device set spans a DCN boundary (multiple slices, or
# multiple processes — parallel.mesh.slice_topology); "on" forces the
# ladder with HOROVOD_HIERARCHICAL_INNER_SIZE (or chips-per-process)
# as the fast-domain size; "off" is the flat collective.
HIERARCHICAL_MODES = ("auto", "on", "off")
# Reference: FUSION_BUFFER_ATOMIC_UNIT alignment (operations.h:52-54).
FUSION_BUFFER_ATOMIC_UNIT = 64
# Reference: STALL_WARNING_TIME 60s (operations.cc:258).
DEFAULT_STALL_WARNING_SECS = 60.0
# Bounded deadline on native-lane collective completion
# (HOROVOD_NEGOTIATION_TIMEOUT, seconds). 0 = reference behavior: warn
# on stalls, wait forever. Non-zero: NativeCore.wait raises a typed
# HorovodTimeoutError past the deadline instead of hanging silently —
# the elastic supervisor (horovod_tpu/elastic/) converts that into a
# relaunch from the last snapshot.
DEFAULT_NEGOTIATION_TIMEOUT_SECS = 0.0
# Elastic snapshot cadence (steps between host-RAM snapshots). Sized so
# a ~1 ms/100 MB d2h snapshot against a ~20 ms step stays well under a
# 2% overhead budget at the default; docs/elastic.md has the cadence
# math (HOROVOD_SNAPSHOT_EVERY).
DEFAULT_SNAPSHOT_EVERY = 100
# Supervisor health-watchdog deadline (HOROVOD_WATCHDOG_TIMEOUT,
# seconds): a rank whose per-window-boundary heartbeat goes stale past
# this is killed, classified "stalled" and the job relaunched from the
# last snapshot. FINITE by default — unlike HOROVOD_NEGOTIATION_TIMEOUT
# (0 = wait forever, the reference's semantics), a silent stall under
# --elastic must terminate. Must exceed the slowest window-boundary
# interval; 300 s covers real training windows with wide margin.
# 0 disables the watchdog.
DEFAULT_WATCHDOG_TIMEOUT_SECS = 300.0


def _env_bool(name: str) -> bool:
    v = os.environ.get(name, "")
    return v not in ("", "0", "false", "False", "FALSE")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _env_choice(name: str, default: str, choices) -> str:
    v = os.environ.get(name, "").strip().lower()
    return v if v in choices else default


@dataclasses.dataclass
class Config:
    """Snapshot of every runtime knob, read once at init."""

    # Gradient-bucket fusion threshold in bytes (HOROVOD_FUSION_THRESHOLD).
    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD
    # Backward-overlapped bucket collectives (HOROVOD_OVERLAP=auto|on|off):
    # issue per-bucket reductions in reverse bucket order, start-all/
    # unpack-later, so XLA's async collective scheduling can hide them
    # under remaining backward compute. "auto" (default) engages whenever
    # the plan has >= 2 buckets and degrades to the legacy single-pass
    # emission otherwise; never changes numerics (docs/benchmarks.md).
    overlap: str = "auto"
    # Bucket-size floor for the reduce-scatter->sharded-update->all-gather
    # form inside overlap mode (HOROVOD_OVERLAP_SCATTER_THRESHOLD, bytes).
    overlap_scatter_threshold: int = DEFAULT_OVERLAP_SCATTER_THRESHOLD
    # Coordinator cycle time in ms — only meaningful for the native eager
    # backend; the XLA path has no background loop (HOROVOD_CYCLE_TIME).
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    # Chrome-trace timeline output path (HOROVOD_TIMELINE).
    timeline_path: str = ""
    timeline_mark_cycles: bool = False
    # Autotuner (HOROVOD_AUTOTUNE / HOROVOD_AUTOTUNE_LOG).
    autotune: bool = False
    autotune_log: str = ""
    # Stall detection (HOROVOD_STALL_CHECK_DISABLE).
    stall_check_disable: bool = False
    stall_warning_secs: float = DEFAULT_STALL_WARNING_SECS
    # Native collective completion deadline (HOROVOD_NEGOTIATION_TIMEOUT,
    # seconds; 0 = wait forever, the reference's semantics).
    negotiation_timeout_secs: float = DEFAULT_NEGOTIATION_TIMEOUT_SECS
    # Elastic snapshot cadence (HOROVOD_SNAPSHOT_EVERY, steps).
    snapshot_every: int = DEFAULT_SNAPSHOT_EVERY
    # Supervisor health-watchdog deadline (HOROVOD_WATCHDOG_TIMEOUT,
    # seconds; 0 disables). Stale-heartbeat workers are killed and the
    # incident classified "stalled".
    watchdog_timeout_secs: float = DEFAULT_WATCHDOG_TIMEOUT_SECS
    # Hierarchical bucket collectives (HOROVOD_HIERARCHICAL=auto|on|off):
    # each fused bucket runs the two-level intra-slice reduce-scatter ->
    # inter-slice DCN exchange -> intra-slice all-gather ladder. "auto"
    # keys off a multi-slice/DCN-present device set (HIERARCHICAL_MODES
    # above; horovod_tpu/jax/fusion.py resolve_hierarchical).
    hierarchical: str = "auto"
    # Hierarchical collectives (legacy boolean spelling): on TPU this
    # selects the explicit two-level ladder (reduce-scatter in the fast
    # domain, cross-reduce, all-gather) rather than NCCL+MPI staging
    # (reference semantics: operations.cc:1284-1436 allreduce,
    # :929-1032 allgather). HOROVOD_HIERARCHICAL_ALLREDUCE=1 is read as
    # HOROVOD_HIERARCHICAL=on.
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Fast-domain (ICI) size for the hierarchical ladder. 0 = auto: the
    # chips-per-process count (the reference's local_comm split,
    # operations.cc:1760-1797). TPU-native extension knob
    # (HOROVOD_HIERARCHICAL_INNER_SIZE) so single-host jobs can pin the
    # ICI/DCN boundary explicitly.
    hierarchical_inner_size: int = 0
    # Log level (HOROVOD_LOG_LEVEL: trace|debug|info|warning|error|fatal).
    log_level: str = "warning"
    log_hide_time: bool = False

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            fusion_threshold=_env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD
            ),
            overlap=_env_choice("HOROVOD_OVERLAP", "auto", OVERLAP_MODES),
            overlap_scatter_threshold=_env_int(
                "HOROVOD_OVERLAP_SCATTER_THRESHOLD",
                DEFAULT_OVERLAP_SCATTER_THRESHOLD,
            ),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS),
            timeline_path=os.environ.get("HOROVOD_TIMELINE", ""),
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            autotune=_env_bool("HOROVOD_AUTOTUNE"),
            autotune_log=os.environ.get("HOROVOD_AUTOTUNE_LOG", ""),
            stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE"),
            stall_warning_secs=_env_float(
                "HOROVOD_STALL_WARNING_TIME", DEFAULT_STALL_WARNING_SECS
            ),
            negotiation_timeout_secs=_env_float(
                "HOROVOD_NEGOTIATION_TIMEOUT",
                DEFAULT_NEGOTIATION_TIMEOUT_SECS,
            ),
            snapshot_every=_env_int(
                "HOROVOD_SNAPSHOT_EVERY", DEFAULT_SNAPSHOT_EVERY
            ),
            watchdog_timeout_secs=_env_float(
                "HOROVOD_WATCHDOG_TIMEOUT", DEFAULT_WATCHDOG_TIMEOUT_SECS
            ),
            hierarchical=_env_choice(
                "HOROVOD_HIERARCHICAL", "auto", HIERARCHICAL_MODES
            ),
            hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"),
            hierarchical_inner_size=_env_int(
                "HOROVOD_HIERARCHICAL_INNER_SIZE", 0
            ),
            log_level=os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
            log_hide_time=_env_bool("HOROVOD_LOG_HIDE_TIME"),
        )


def round_to_atomic_unit(nbytes: int) -> int:
    """Round a buffer size up to the fusion atomic unit.

    Mirrors the reference's FUSION_BUFFER_ATOMIC_UNIT sizing rule
    (horovod/common/operations.cc:742-764) so bucket boundaries stay aligned
    for the TPU lane width as well (64 B = 16 f32 lanes).
    """
    unit = FUSION_BUFFER_ATOMIC_UNIT
    return (nbytes + unit - 1) // unit * unit
