"""Process-global framework state.

TPU-native analogue of the reference's ``HorovodGlobalState``
(horovod/common/operations.cc:115-249). On TPU there is no background
coordinator thread for the compiled path — XLA executes collectives in
program order across identical SPMD replicas — so the state reduces to:

* the device set and the default 1-D ``"hvd"`` mesh over it,
* process/topology info (the reference derived rank/local_rank/size by
  splitting MPI_COMM_WORLD, operations.cc:1748-1797; we read the JAX
  runtime's pod topology),
* a config snapshot, the timeline, and the (optional) native eager core.

Rank semantics (documented divergence from the reference): the unit of
parallelism is the **chip**. ``size()`` is the number of chips in the job and
inside an SPMD region ``rank()`` is the chip's mesh index. Outside SPMD
regions there is one Python rank per *process*; ``rank()`` returns the global
index of the process's first chip so that ``rank() == 0`` keeps its
Horovod meaning of "the process that logs/checkpoints".
"""

from __future__ import annotations

import contextvars
import threading
from typing import Any, Optional

from horovod_tpu.common.config import Config
from horovod_tpu.common.exceptions import NotInitializedError


class GlobalState:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.initialized = False
        self.config: Config = Config()
        # jax.sharding.Mesh over all devices with axis "hvd".
        self.mesh: Any = None
        self.devices: list = []
        self.process_index: int = 0
        self.process_count: int = 1
        self.local_device_count: int = 0
        self.global_device_count: int = 0
        # Mesh index of this process's first chip: the value rank() reports
        # outside SPMD regions (so rank()==0 gates logging/checkpointing).
        self.first_device_index: int = 0
        # Optional sub-group of ranks passed to init(ranks) — reference
        # horovod_init(ranks, nranks) operations.cc:1728-1746.
        self.subset_ranks: Optional[list] = None
        # Aux subsystems, created lazily at init.
        self.timeline: Any = None
        self.autotuner: Any = None
        # Native eager core handle (ctypes), used by the torch/numpy
        # eager backend when running multi-process on CPU.
        self.native: Any = None

    def require_init(self) -> None:
        if not self.initialized:
            raise NotInitializedError(
                "horovod_tpu has not been initialized; call hvd.init() first."
            )


_global_state = GlobalState()


def global_state() -> GlobalState:
    return _global_state


# Axis name of the enclosing SPMD region, set by horovod_tpu.parallel.spmd
# when tracing a per-chip program. When set, collectives become
# jax.lax collectives over this axis and rank() returns the traced
# axis index.
_spmd_axis: contextvars.ContextVar = contextvars.ContextVar(
    "horovod_tpu_spmd_axis", default=None
)


def current_spmd_axis():
    return _spmd_axis.get()


def set_spmd_axis(axis):
    return _spmd_axis.set(axis)


def reset_spmd_axis(token) -> None:
    _spmd_axis.reset(token)
