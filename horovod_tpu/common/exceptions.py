"""Error taxonomy mirroring the reference's Status codes.

Reference: horovod/common/common.h:38-75 defines StatusType
{OK, UNKNOWN_ERROR, PRECONDITION_ERROR, ABORTED, INVALID_ARGUMENT} — we expose
them as exception classes so Python callers get idiomatic errors while tests
can assert on the same failure classes the reference's negotiation produces
(e.g. mismatched shapes/dtypes across ranks, operations.cc:321-523).
"""


class HorovodError(Exception):
    """Base class for all framework errors (UNKNOWN_ERROR)."""


class HorovodInternalError(HorovodError):
    """Unexpected internal failure."""


class NotInitializedError(HorovodError):
    """An API requiring ``hvd.init()`` was called before initialization.

    Reference: horovod/common/operations.cc:2441-2468 returns -1 / raises when
    rank()/size() are called before init.
    """


class PreconditionError(HorovodError):
    """PRECONDITION_ERROR: op submitted in an invalid state (e.g. duplicate
    in-flight tensor name, reference operations.cc:2497-2506)."""


class AbortedError(HorovodError):
    """ABORTED: collective cancelled by coordinated shutdown
    (reference SHUT_DOWN_ERROR, operations.cc:263-268)."""


class InvalidArgumentError(HorovodError, ValueError):
    """INVALID_ARGUMENT: rank-inconsistent dtype/shape/device/root detected by
    negotiation (reference ConstructMPIResponse, operations.cc:321-523)."""


class HorovodTimeoutError(HorovodError):
    """A native collective sat past its bounded deadline
    (``HOROVOD_NEGOTIATION_TIMEOUT``) without completing.

    The reference only *warned* on stalls (CheckForStalledTensors,
    operations.cc:1625-1672) and then hung forever; the elastic
    subsystem (:mod:`horovod_tpu.elastic`) needs a typed, attributable
    failure instead — the supervisor treats it like a crashed rank and
    relaunches from the last snapshot. Carries the observing rank and
    the stalled tensor's name; the op may still be in flight, so the
    only safe recovery is process exit + relaunch."""

    def __init__(self, message: str, rank: int = -1,
                 tensor_name: str = ""):
        super().__init__(message)
        self.rank = rank
        self.tensor_name = tensor_name


class StalledTensorWarning(UserWarning):
    """Emitted when a tensor sits un-negotiated past the stall deadline
    (reference CheckForStalledTensors, operations.cc:1625-1672)."""
