"""Minimal jax version-compat layer.

The framework targets current jax (public ``jax.shard_map``,
``lax.axis_size``), but must still import and run its core SPMD path on
older runtimes (e.g. CI/sandbox images pinned to the 0.4.x era, where
those names live elsewhere or do not exist). Policy: one explicit
``install()`` at package import, polyfilling ONLY missing names with
semantically identical implementations — never overriding anything the
runtime already provides.

Polyfills:

* ``jax.lax.axis_size(name)`` — the named-axis size inside an SPMD
  region. Older jax spells this ``lax.psum(1, name)``, which constant-
  folds to a static Python int at trace time (the long-standing idiom
  the newer helper replaced), so the polyfill is exact — including for
  shape arithmetic.

The ``jax.shard_map`` vs ``jax.experimental.shard_map`` (check_vma vs
check_rep) split is resolved in :mod:`horovod_tpu.parallel.spmd`, next
to its single call site.
"""

from __future__ import annotations


def install() -> None:
    """Idempotently install the polyfills for names this jax lacks."""
    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            """Polyfill of lax.axis_size: psum of the constant 1 over
            the axis constant-folds to the static axis size."""
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size
        # lax re-exports live under jax.lax via the same module object;
        # nothing else to patch.
        assert hasattr(jax.lax, "axis_size")
