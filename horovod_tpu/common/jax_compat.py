"""Minimal jax version-compat layer.

The framework targets current jax (public ``jax.shard_map``,
``lax.axis_size``), but must still import and run its core SPMD path on
older runtimes (e.g. CI/sandbox images pinned to the 0.4.x era, where
those names live elsewhere or do not exist). Policy: one explicit
``install()`` at package import, polyfilling ONLY missing names with
semantically identical implementations — never overriding anything the
runtime already provides.

Polyfills:

* ``jax.lax.axis_size(name)`` — the named-axis size inside an SPMD
  region. Older jax spells this ``lax.psum(1, name)``, which constant-
  folds to a static Python int at trace time (the long-standing idiom
  the newer helper replaced), so the polyfill is exact — including for
  shape arithmetic.
* ``jax.enable_x64`` — the top-level x64-override context manager; the
  0.4.x era kept the identical object in ``jax.experimental``.

The ``jax.shard_map`` vs ``jax.experimental.shard_map`` (check_vma vs
check_rep) split is resolved in :mod:`horovod_tpu.parallel.spmd`, next
to its single call site.

Pallas names are polyfilled lazily via :func:`pallas_tpu` (pallas is a
heavy import most entrypoints never touch, so ``install()`` must not
pay for it at package import).
"""

from __future__ import annotations


def install() -> None:
    """Idempotently install the polyfills for names this jax lacks."""
    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            """Polyfill of lax.axis_size: psum of the constant 1 over
            the axis constant-folds to the static axis size."""
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size
        # lax re-exports live under jax.lax via the same module object;
        # nothing else to patch.
        assert hasattr(jax.lax, "axis_size")

    if not hasattr(lax, "pcast"):
        def pcast(x, axis_name, to=None):
            """Polyfill of lax.pcast for runtimes without vma typing:
            pcast is identity-VALUED by contract (it only changes the
            static varying-axes type), and on a runtime with no such
            type system the identity is the whole operation."""
            del axis_name, to
            return x

        lax.pcast = pcast

    if not hasattr(jax, "enable_x64"):
        # Current jax exposes the x64-override context manager at top
        # level; the 0.4.x era kept it in jax.experimental. Same object,
        # same semantics — re-export, never wrap.
        from jax.experimental import enable_x64

        jax.enable_x64 = enable_x64

    if not hasattr(jax, "shard_map"):
        def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kwargs):
            """Polyfill of the public jax.shard_map over its 0.4.x home
            (jax.experimental.shard_map), mapping the current
            ``check_vma`` kwarg onto the old ``check_rep`` (same
            replication/varying check, renamed). Imports lazily: the
            experimental module is not paid for at package import."""
            import inspect

            from jax.experimental.shard_map import shard_map as esm

            if check_vma is not None:
                key = ("check_vma"
                       if "check_vma" in inspect.signature(esm).parameters
                       else "check_rep")
                kwargs[key] = check_vma
            return esm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kwargs)

        jax.shard_map = shard_map


def pallas_tpu():
    """``jax.experimental.pallas.tpu`` with current-jax names polyfilled.

    Current jax spells the Mosaic compile options ``pltpu.CompilerParams``;
    the 0.4.x era shipped the identical class as ``TPUCompilerParams``.
    Alias only when missing (same never-override policy as install());
    kernels import pltpu through this helper instead of directly.
    """
    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams
    return pltpu
