"""Framework-neutral core: state, config, lifecycle, error taxonomy.

Structural counterpart of the reference's horovod/common/ (operations.cc,
common.h, __init__.py). The compiled-path coordinator lives in XLA program
order; the eager-path native core lives in csrc/ and is loaded lazily by
horovod_tpu.common.native.
"""

from horovod_tpu.common.basics import (  # noqa: F401
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.common.config import Config  # noqa: F401
from horovod_tpu.common.exceptions import (  # noqa: F401
    AbortedError,
    HorovodError,
    HorovodInternalError,
    InvalidArgumentError,
    NotInitializedError,
    PreconditionError,
)
