"""Lifecycle + topology API: init/shutdown/rank/size/local_rank/local_size.

Parity surface of the reference's ``HorovodBasics``
(horovod/common/__init__.py:51-154) and the C init API
(horovod/common/operations.cc:2413-2468), bound to the TPU pod topology
instead of MPI_COMM_WORLD:

* ``init()``            -> record jax device/process topology, build the
                           default 1-D "hvd" mesh, start aux subsystems.
* ``rank()/size()``     -> chip-granular (see state.py docstring); inside an
                           SPMD region rank() is the traced mesh index.
* ``local_rank()/local_size()``  -> position within this host/process.
* ``mpi_threads_supported()``    -> False (no MPI anywhere), kept for parity.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional, Sequence

from horovod_tpu.common.config import Config
from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.common.state import current_spmd_axis, global_state


def init(comm: Optional[Sequence[int]] = None, devices=None) -> None:
    """Initialize the framework.

    ``comm`` optionally restricts the job to a subset of ranks, mirroring
    ``horovod_init(ranks, nranks)`` (reference operations.cc:1728-1746).
    Ranks are chips on the SPMD lane, so ``comm=[0, 2]`` builds the
    "hvd" mesh from chips 0 and 2 of the global device order and
    ``size()`` becomes 2.

    ``devices`` optionally restricts the mesh to an explicit device list
    (TPU extension; the chip-level analogue of the ranks subset).

    Safe to call more than once (reference InitializeHorovodOnce,
    operations.cc:2384-2401).
    """
    state = global_state()
    with state.lock:
        if state.initialized:
            return
        import jax

        # Multi-host: when the launcher provides a jax coordinator
        # (HOROVOD_JAX_COORDINATOR, set by `hvdrun --jax`), join the jax
        # distributed runtime BEFORE the first backend query so every
        # process sees the global device set — the analogue of the
        # reference joining MPI_COMM_WORLD at init (operations.cc:1724).
        # TPU pods that pre-initialize via the runtime env need nothing
        # here, and single-process usage stays zero-config.
        jax_coord = os.environ.get("HOROVOD_JAX_COORDINATOR", "")
        if jax_coord and os.environ.get("HOROVOD_SIZE"):
            # Skip only when the distributed runtime is ALREADY up (e.g.
            # the TPU pod runtime); a connect failure must propagate —
            # swallowing it would leave this rank world-size 1 while its
            # peers block on the barrier, with zero diagnostics.
            # jax.distributed.is_initialized() is a recent addition; the
            # 0.4.x era exposes the same fact as the singleton state's
            # live client (the exact check is_initialized wraps).
            if hasattr(jax.distributed, "is_initialized"):
                already_up = jax.distributed.is_initialized()
            else:
                from jax._src import distributed as _dist

                already_up = (
                    getattr(_dist.global_state, "client", None)
                    is not None)
            if not already_up:
                jax.distributed.initialize(
                    coordinator_address=jax_coord,
                    num_processes=int(os.environ["HOROVOD_SIZE"]),
                    process_id=int(os.environ.get("HOROVOD_RANK", "0")),
                )
        state.config = Config.from_env()
        state.devices = list(devices) if devices is not None else list(jax.devices())
        if comm is not None:
            # Ranks are chips on the SPMD lane, so the reference's
            # rank-subset semantics (horovod_init(ranks, nranks),
            # operations.cc:1728-1746) map to subsetting the mesh device
            # list: hvd.init(comm=[0, 2]) builds a 2-chip job from chips
            # 0 and 2 of the global order.
            bad = [r for r in comm if not 0 <= r < len(state.devices)]
            if bad:
                raise InvalidArgumentError(
                    f"comm ranks {bad} out of range for "
                    f"{len(state.devices)} devices"
                )
            state.devices = [state.devices[r] for r in comm]
            if jax.process_count() > 1 and not any(
                getattr(d, "process_index", 0) == jax.process_index()
                for d in state.devices
            ):
                # A process owning NO chip of the subset has no rank; two
                # such processes would otherwise both report rank 0 and
                # double-run every rank-0-gated action (checkpoint writes,
                # logs). Exclude the process at launch instead.
                raise InvalidArgumentError(
                    "hvd.init(comm=...) selected no chips owned by this "
                    "process; multi-host subsets must cover every "
                    "participating process (exclude the others at the "
                    "launcher level)."
                )
        state.process_index = jax.process_index()
        state.process_count = jax.process_count()
        if devices is not None or comm is not None:
            local_indices = [
                i
                for i, d in enumerate(state.devices)
                if getattr(d, "process_index", 0) == jax.process_index()
            ]
            state.local_device_count = len(local_indices)
            state.global_device_count = len(state.devices)
            state.first_device_index = local_indices[0] if local_indices else 0
        else:
            state.local_device_count = jax.local_device_count()
            state.global_device_count = jax.device_count()
            state.first_device_index = jax.process_index() * jax.local_device_count()
        state.subset_ranks = list(comm) if comm is not None else None

        from jax.sharding import Mesh
        import numpy as np

        from horovod_tpu.parallel.logical import DATA_AXIS

        state.mesh = Mesh(np.asarray(state.devices), (DATA_AXIS,))

        from horovod_tpu.utils.timeline import Timeline

        state.timeline = Timeline(
            state.config.timeline_path or None,
            mark_cycles=state.config.timeline_mark_cycles,
            enabled_rank=state.process_index == 0,
        )

        if state.config.autotune:
            # HOROVOD_AUTOTUNE on the SPMD lane: sweep the fusion threshold
            # against measured step rate (reference parameter_manager.h:
            # 211-217 scoring semantics; see horovod_tpu/jax/autotune.py).
            from horovod_tpu.jax.autotune import StepAutotuner

            # Log on process 0 only (the reference gated tuner logging to
            # the coordinator rank); every process still RUNS the tuner so
            # generations stay in lockstep.
            state.autotuner = StepAutotuner(
                state.config,
                log_path=(
                    state.config.autotune_log
                    if state.process_index == 0
                    else ""
                ),
            )

        state.initialized = True
        atexit.register(shutdown)


def shutdown() -> None:
    """Coordinated shutdown (reference horovod_shutdown,
    operations.cc:2425-2439). Flushes the timeline and drops state."""
    state = global_state()
    with state.lock:
        if not state.initialized:
            return
        if state.timeline is not None:
            state.timeline.close()
        if state.autotuner is not None:
            state.autotuner.close()
            state.autotuner = None
        if state.native is not None:
            state.native.shutdown()
            state.native = None
        state.initialized = False
        state.mesh = None
        state.devices = []


def is_initialized() -> bool:
    return global_state().initialized


def size() -> int:
    """Total number of chips in the job (reference horovod_size,
    operations.cc:2448, where the unit was one process == one GPU)."""
    state = global_state()
    state.require_init()
    return state.global_device_count


def local_size() -> int:
    """Chips attached to this process (reference horovod_local_size,
    operations.cc:2456)."""
    state = global_state()
    state.require_init()
    return state.local_device_count


def rank():
    """Global rank.

    Inside an SPMD region: the traced chip index along the "hvd" mesh axis.
    Outside: the global index of this process's first chip (so rank()==0
    selects the logging/checkpointing process, reference horovod_rank
    operations.cc:2441).
    """
    state = global_state()
    state.require_init()
    axis = current_spmd_axis()
    if axis is not None:
        from jax import lax

        return lax.axis_index(axis)
    return state.first_device_index


def local_rank():
    """Rank within this process/host (reference horovod_local_rank,
    operations.cc:2444). Traced inside SPMD regions."""
    state = global_state()
    state.require_init()
    axis = current_spmd_axis()
    if axis is not None:
        from jax import lax

        # Assumes a uniform chips-per-process layout (true for every TPU
        # slice topology; device subsets that break it would need a
        # per-process constant, which would diverge the SPMD programs).
        return lax.axis_index(axis) % max(state.local_device_count, 1)
    return 0


def process_rank() -> int:
    """Index of this process (TPU extension; == jax.process_index())."""
    state = global_state()
    state.require_init()
    return state.process_index


def process_count() -> int:
    """Number of processes (TPU extension; == jax.process_count())."""
    state = global_state()
    state.require_init()
    return state.process_count


def mpi_threads_supported() -> bool:
    """Parity shim for horovod_mpi_threads_supported (operations.cc:2462-2468).

    There is no MPI in this framework; always False.
    """
    global_state().require_init()
    return False


def mesh():
    """The default 1-D device mesh with axis name "hvd"."""
    state = global_state()
    state.require_init()
    return state.mesh


def check_extension(ext_name: str, ext_env_var: str, path=None) -> None:
    """Parity shim for HorovodBasics.check_extension
    (reference horovod/common/__init__.py:43-48): raise if a binding was
    disabled at build time. All of our bindings are pure-config, so the
    only failure mode is an explicit opt-out via the env var."""
    if os.environ.get(ext_env_var, "") in ("0", "false", "False"):
        raise ImportError(
            f"Extension {ext_name} has been disabled via {ext_env_var}"
        )
