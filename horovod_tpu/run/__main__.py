"""CLI: ``python -m horovod_tpu.run -np N [-H hosts] cmd args...``

Same CLI as the installed ``hvdrun`` console script; the body lives in
:mod:`horovod_tpu.run.launcher`.
"""

import sys

from horovod_tpu.run.launcher import main

if __name__ == "__main__":
    sys.exit(main())
