"""horovod_tpu.run — the job launcher.

Role parity with the reference's two launch paths:

* ``horovodrun``-style CLI (``python -m horovod_tpu.run -np N cmd...``) —
  the reference delegated this to ``mpirun`` (docs/running.md); here the
  launcher owns process placement directly.
* ``horovod_tpu.run.run(fn, np=N)`` — the ``horovod.spark.run`` analogue
  (reference spark/__init__.py:80-196): ship a pickled function to N
  workers, run it, collect per-rank results, fail fast on any error.

Each worker gets the Horovod environment (HOROVOD_RANK/SIZE/LOCAL_RANK/
LOCAL_SIZE/CONTROLLER/SECRET), replacing the reference's MPI-provided
COMM_WORLD (operations.cc:1748-1797). Multi-host: ``-H host:n,...`` execs
workers over ssh with the same env (driver must be reachable).
"""

from __future__ import annotations

import os
import random
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import dataclasses

from horovod_tpu.run.driver import (Driver, WorkerExit, classify_exit,
                                    EXIT_CLEAN, EXIT_PREEMPTED,
                                    EXIT_RESIZED, EXIT_USAGE)
from horovod_tpu.run.network import make_secret_key


class LaunchError(RuntimeError):
    def __init__(self, message: str, failures: Optional[dict] = None):
        super().__init__(message)
        self.failures = failures or {}


@dataclasses.dataclass
class JobResult:
    """Outcome of one :func:`launch_job` attempt, with PER-WORKER exit
    codes instead of the single collapsed code the kill-all used to
    return. ``trigger`` is the first worker observed failing (the one
    whose death caused the kill-all); the other ranks' codes then
    reflect the supervisor's SIGTERM, not their own fault.
    ``stalled_ranks`` maps each rank the health watchdog killed for a
    stale heartbeat to the observed heartbeat age (the time-to-detect
    evidence the elastic recovery metrics stamp). ``pre_kill_codes``
    holds every non-clean exit observed BEFORE the kill-all — these
    ranks died on their own, so (unlike ``exit_codes``, polluted by
    the teardown SIGTERMs) they tell the elastic supervisor how many
    workers were actually lost when it decides the shrink size."""

    exit_codes: Dict[int, Optional[int]]
    trigger: Optional[WorkerExit] = None
    stalled_ranks: Dict[int, float] = dataclasses.field(
        default_factory=dict)
    pre_kill_codes: Dict[int, int] = dataclasses.field(
        default_factory=dict)

    @property
    def code(self) -> int:
        return self.trigger.code if self.trigger is not None else EXIT_CLEAN

    @property
    def category(self) -> str:
        """clean | usage | preempted | resized | stalled | crashed —
        the trigger worker's classification (run.driver.classify_exit,
        plus the watchdog's stalled mark)."""
        if self.trigger is not None:
            return self.trigger.category
        return classify_exit(self.code)

    def describe(self) -> str:
        if self.trigger is None:
            return "all ranks exited cleanly"
        return (f"rank {self.trigger.rank} "
                f"{self.trigger.category} (exit {self.trigger.code}); "
                "per-rank codes "
                + str({r: c for r, c in sorted(self.exit_codes.items())}))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("0.0.0.0", 0))
        return s.getsockname()[1]


def _worker_env(base: Dict[str, str], rank: int, size: int, local_rank: int,
                local_size: int, controller: str, driver: str,
                secret_hex: str,
                jax_coordinator: str = "") -> Dict[str, str]:
    env = dict(base)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CONTROLLER": controller,
        "HOROVOD_DRIVER": driver,
        "HOROVOD_SECRET": secret_hex,
    })
    if jax_coordinator:
        # hvd.init() joins the jax distributed runtime at this address
        # before its first backend query, so every process sees the
        # GLOBAL device set (horovod_tpu/common/basics.py).
        env["HOROVOD_JAX_COORDINATOR"] = jax_coordinator
    return env


def _parse_hosts(hosts: str) -> List[tuple]:
    """Parse ``host1:4,host2:4`` into [(host, slots), ...]
    (reference horovodrun -H syntax)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, slots = part.partition(":")
        out.append((host, int(slots) if slots else 1))
    return out


def _spawn_local(cmd: Sequence[str], env: Dict[str, str]) -> subprocess.Popen:
    # New process group so one kill() reaps the whole rank's tree
    # (reference safe_shell_exec process-group discipline).
    return subprocess.Popen(list(cmd), env=env, start_new_session=True)


# Machine-local variables never forwarded to remote ranks; everything else
# in the job env goes over so all ranks of one job see one environment.
_SSH_ENV_DENY = ("SSH_", "DISPLAY", "HOSTNAME", "PWD", "OLDPWD", "SHLVL",
                 "TMPDIR", "XDG_", "DBUS_", "HOME", "LOGNAME", "USER", "_")


_SSH_READY_MARKER = b"__HVD_ECHO_OFF__"


def _spawn_ssh(host: str, cmd: Sequence[str],
               env: Dict[str, str]) -> subprocess.Popen:
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env.items()
        if not k.startswith(_SSH_ENV_DENY) and k != "HOROVOD_SECRET"
        and "\n" not in v)
    # The HMAC secret must never appear on a command line (argv is world-
    # readable via /proc on the remote host); ship it over stdin instead.
    # The -tt pty would echo that stdin line back into the launcher's
    # stdout (and thus scrollback/job logs), so the remote disables echo
    # and prints a marker; the launcher only writes the secret AFTER the
    # marker arrives (writing earlier would race the stty and be echoed
    # by the default line discipline).
    marker = _SSH_READY_MARKER.decode()
    remote = (f"stty -echo 2>/dev/null; printf '{marker}\\n'; "
              "IFS= read -r HOROVOD_SECRET && export HOROVOD_SECRET && "
              f"cd {shlex.quote(os.getcwd())} && env {exports} "
              + " ".join(shlex.quote(c) for c in cmd))
    # -tt forces a pty so killing the local ssh client HUPs the remote
    # process tree — the fail-fast kill works across hosts.
    proc = subprocess.Popen(["ssh", "-tt", "-o", "BatchMode=yes", host,
                             remote], start_new_session=True,
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE)

    def feed_secret_then_pump():
        out = proc.stdout
        line = b""
        while True:  # wait for the echo-off marker (or early EOF)
            ch = out.read(1)
            if not ch:
                return  # ssh died before the marker; supervisor reaps it
            if ch == b"\n":
                if _SSH_READY_MARKER in line:
                    break
                sys.stdout.buffer.write(line + b"\n")
                sys.stdout.buffer.flush()
                line = b""
            else:
                line += ch
        try:
            proc.stdin.write((env.get("HOROVOD_SECRET", "") + "\n").encode())
            proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return
        while True:  # stream the worker's output to the launcher's stdout
            chunk = out.read(4096)
            if not chunk:
                return
            sys.stdout.buffer.write(chunk)
            sys.stdout.buffer.flush()

    pump = threading.Thread(target=feed_secret_then_pump, daemon=True)
    pump.start()
    proc._hvd_pump_thread = pump  # joined by _drain_output at job end
    return proc


def _drain_output(procs: List[subprocess.Popen], timeout: float = 5.0) -> None:
    """Join ssh stdout pump threads so the tail of remote worker output is
    flushed to the launcher's stdout before launch_command returns."""
    deadline = time.monotonic() + timeout
    for p in procs:
        t = getattr(p, "_hvd_pump_thread", None)
        if t is not None:
            t.join(max(0.1, deadline - time.monotonic()))


def _kill_all(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    deadline = time.monotonic() + 5
    for p in procs:
        try:
            p.wait(max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    # Reap the SIGKILLed stragglers so callers (notably the --restarts
    # relaunch loop) never start a new attempt while an old local worker
    # still holds its device lock or checkpoint file. SIGKILL cannot be
    # blocked; the wait only stalls on uninterruptible (D-state) I/O, so
    # bound it and report rather than hang the launcher.
    reap_deadline = time.monotonic() + 10
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(max(0.1, reap_deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                print(f"hvdrun: worker pid {p.pid} did not exit after "
                      "SIGKILL (uninterruptible I/O?); proceeding",
                      file=sys.stderr, flush=True)


def spawn_worker(cmd: Sequence[str],
                 env: Optional[Dict[str, str]] = None) -> subprocess.Popen:
    """Spawn ONE supervised worker process — the single-process lane of
    :func:`launch_job`'s placement discipline (its own session/process
    group, so one ``killpg`` reaps the worker's whole tree). The caller
    owns supervision and classification; the serving fleet
    (:mod:`horovod_tpu.serve.fleet`, ``transport="process"``) pairs
    this with :class:`~horovod_tpu.run.driver.WorkerExit` /
    :func:`~horovod_tpu.run.driver.classify_exit` so replica and
    training incidents speak one taxonomy."""
    return _spawn_local(cmd, dict(env if env is not None
                                  else os.environ))


def spawn_worker_ssh(host: str, cmd: Sequence[str],
                     env: Optional[Dict[str, str]] = None
                     ) -> subprocess.Popen:
    """Spawn ONE supervised worker on a REMOTE host over ssh — the
    multi-host lane of :func:`spawn_worker`, used by the serving
    fleet's ``transport="tcp"`` placement. Reuses the launcher's ssh
    discipline (:func:`_spawn_ssh`): ``-tt`` forces a pty so killing
    the returned LOCAL ssh client's process group
    (:func:`kill_worker` / :func:`terminate_worker`) HUPs the remote
    process tree — the fail-fast kill works across hosts — and the
    ``HOROVOD_SECRET`` entry of ``env`` ships over stdin after an
    echo-off marker, never on the remote argv (world-readable via
    /proc). Caveat the caller owns: the returned Popen is the ssh
    CLIENT, so its exit code is the remote command's only when the
    remote exits normally — a signal-killed remote (or a dead ssh
    session) reports 255/-signum, and the fleet classifies those from
    its own evidence instead (docs/serving.md "Multi-host fleet")."""
    return _spawn_ssh(host, list(cmd),
                      dict(env if env is not None else os.environ))


def kill_worker(proc: subprocess.Popen,
                timeout: float = 5.0) -> Optional[int]:
    """SIGKILL one worker's process group and reap it (bounded — a
    D-state wait must not hang the caller; see :func:`_kill_all`).
    Returns the observed exit code, or None when the process could not
    be reaped within ``timeout``."""
    if proc.poll() is None:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None
    return proc.returncode


def terminate_worker(proc: subprocess.Popen,
                     term_timeout: float = 2.0,
                     kill_timeout: float = 5.0) -> Optional[int]:
    """Graceful-teardown escalation for one worker: SIGTERM the process
    group, wait ``term_timeout``, SIGKILL stragglers, reap — the
    :func:`_kill_all` ladder, single-process edition (the fleet's
    ``close()`` uses it after the shutdown RPC so a wedged replica can
    never zombie). Returns the exit code, or None if unreapable."""
    if proc.poll() is None:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(term_timeout)
        except subprocess.TimeoutExpired:
            return kill_worker(proc, kill_timeout)
    return proc.returncode


def launch_command(cmd: Sequence[str], np: int,
                   hosts: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None,
                   jax_distributed: bool = False) -> int:
    """Run ``cmd`` as an N-rank job; returns the job's exit code
    (back-compat wrapper over :func:`launch_job`)."""
    return launch_job(cmd, np, hosts=hosts, env=env,
                      jax_distributed=jax_distributed).code


def launch_job(cmd: Sequence[str], np: int,
               hosts: Optional[str] = None,
               env: Optional[Dict[str, str]] = None,
               jax_distributed: bool = False,
               watchdog=None) -> JobResult:
    """Run ``cmd`` as an N-rank job; returns a :class:`JobResult` with
    per-worker exit codes and the classified trigger failure.

    Fails fast: the first non-zero rank kills the rest (the reference
    relied on mpirun for exactly this) — but unlike the reference's
    collapsed mpirun code, the result records WHICH rank died and HOW
    (clean / usage / preempted / resized / stalled / crashed), so the
    elastic supervisor can decide relaunch-vs-fail per incident.

    ``watchdog`` (an :class:`~horovod_tpu.elastic.supervisor.
    HealthWatchdog` or anything with its ``check(ranks) -> {rank:
    age}``) rides this supervision poll: ranks it reports as
    heartbeat-stale are SIGKILLed here and their exits marked
    *stalled* — a silently-hung worker becomes an ordinary classified
    incident instead of an eternal wait.

    ``jax_distributed``: also stand up a jax coordination service address
    (HOROVOD_JAX_COORDINATOR) so each worker's ``hvd.init()`` joins one
    global jax device mesh — the SPMD lane spanning all workers' chips,
    the way mpirun+NCCL spanned all GPUs in the reference.
    """
    base_env = dict(env if env is not None else os.environ)
    secret_hex = make_secret_key().hex()

    placements: List[tuple] = []  # (host or None, local_rank, local_size)
    if hosts:
        parsed = _parse_hosts(hosts)
        total = sum(s for _, s in parsed)
        if total != np:
            raise LaunchError(f"-H slots ({total}) != -np ({np})")
        for host, slots in parsed:
            for lr in range(slots):
                placements.append((host, lr, slots))
    else:
        placements = [(None, r, np) for r in range(np)]

    first_host = placements[0][0]
    if first_host is None or first_host in ("localhost", "127.0.0.1"):
        controller_host = "127.0.0.1"
        controller_port = _free_port()  # rank 0 binds on this machine
    else:
        # Rank 0 binds on a remote host we cannot probe; pick from the
        # high ephemeral range and let its init report a bind conflict.
        controller_host = first_host
        controller_port = random.randint(20000, 59999)
    controller = f"{controller_host}:{controller_port}"
    jax_coordinator = ""
    if jax_distributed:
        jax_port = controller_port
        while jax_port == controller_port:  # two services, two ports
            jax_port = (_free_port() if controller_host == "127.0.0.1"
                        else random.randint(20000, 59999))
        jax_coordinator = f"{controller_host}:{jax_port}"

    procs: List[subprocess.Popen] = []
    try:
        for rank, (host, local_rank, local_size) in enumerate(placements):
            wenv = _worker_env(base_env, rank, np, local_rank, local_size,
                               controller, "", secret_hex, jax_coordinator)
            if host is None or host in ("localhost", "127.0.0.1"):
                procs.append(_spawn_local(cmd, wenv))
            else:
                procs.append(_spawn_ssh(host, cmd, wenv))
        # Supervise: poll until all exit or one fails.
        stalled: Dict[int, float] = {}
        while True:
            codes = [p.poll() for p in procs]
            if watchdog is not None:
                live = [r for r, c in enumerate(codes) if c is None]
                for rank, age in watchdog.check(live).items():
                    # A stale heartbeat means the worker is silently
                    # wedged — possibly mid-collective, where SIGTERM's
                    # graceful drain would hang too. SIGKILL converts
                    # the hang into a classifiable incident.
                    print(f"hvdrun: health watchdog: rank {rank} "
                          f"heartbeat stale for {age:.1f}s (timeout "
                          f"{watchdog.timeout:g}s) — killing the "
                          "stalled worker", file=sys.stderr, flush=True)
                    stalled[rank] = age
                    watchdog.kills[rank] = age
                    try:
                        os.killpg(os.getpgid(procs[rank].pid),
                                  signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                if stalled:
                    codes = [p.poll() for p in procs]
            bad_ranks = [r for r, c in enumerate(codes)
                         if c not in (None, 0)]
            if bad_ranks:
                # The lowest failing rank at this poll is the trigger;
                # its code (not the peers' kill-all SIGTERMs) classifies
                # the incident — a watchdog-killed rank wins the tie so
                # the incident is classed *stalled*, not by whatever
                # exit its SIGKILL raced. Record every code observed
                # BEFORE the kill so self-inflicted exits stay
                # distinguishable.
                first = min((r for r in bad_ranks if r in stalled),
                            default=bad_ranks[0])
                trigger = WorkerExit(first, codes[first],
                                     stalled=first in stalled)
                pre_kill = {r: c for r, c in enumerate(codes)
                            if c not in (None, 0)}
                _kill_all(procs)
                _drain_output(procs)
                return JobResult(
                    exit_codes={r: p.poll()
                                for r, p in enumerate(procs)},
                    trigger=trigger, stalled_ranks=dict(stalled),
                    pre_kill_codes=pre_kill)
            if all(c == 0 for c in codes):
                _drain_output(procs)
                return JobResult(
                    exit_codes=dict(enumerate(codes)), trigger=None)
            time.sleep(0.05)
    except KeyboardInterrupt:
        _kill_all(procs)
        raise
    finally:
        if any(p.poll() is None for p in procs):
            _kill_all(procs)


def run(fn, args: tuple = (), kwargs: Optional[dict] = None, np: int = 1,
        env: Optional[Dict[str, str]] = None,
        start_timeout: float = 120.0,
        run_timeout: float = 600.0) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` local ranks; returns the list
    of per-rank return values, rank-ordered (reference horovod.spark.run
    semantics, spark/__init__.py:80-196)."""
    key = make_secret_key()
    driver = Driver(np, key, fn=fn, args=args, kwargs=kwargs)
    base_env = dict(env if env is not None else os.environ)
    secret_hex = key.hex()
    controller = f"127.0.0.1:{_free_port()}"
    # Publish EVERY candidate endpoint (loopback + per-NIC addresses);
    # each worker probes for the first one that answers an authenticated
    # Ping before registering — reference Spark interface discovery
    # (spark/__init__.py:33-39,123-140). Local-only today, but ssh-remote
    # workers get the multi-NIC story for free.
    from horovod_tpu.run.network import candidate_addresses

    driver_addr = ",".join(candidate_addresses(driver.port))

    procs: List[subprocess.Popen] = []
    try:
        for rank in range(np):
            wenv = _worker_env(base_env, rank, np, rank, np, controller,
                               driver_addr, secret_hex)
            procs.append(_spawn_local(
                [sys.executable, "-m", "horovod_tpu.run.task_exec"], wenv))
        if not driver.wait_registered(start_timeout):
            raise LaunchError(
                f"timed out after {start_timeout}s waiting for "
                f"{np} workers to register")

        def worker_died():
            return any(p.poll() not in (None, 0) for p in procs)

        results = driver.wait_results(run_timeout, should_abort=worker_died)
        failures = {r: res.payload for r, res in results.items()
                    if not res.success}
        if failures:
            first = min(failures)
            raise LaunchError(
                f"rank {first} failed:\n{failures[first]}", failures)
        if len(results) < np:
            dead = [r for r, p in enumerate(procs)
                    if p.poll() not in (None, 0)]
            if dead:
                raise LaunchError(
                    f"rank(s) {dead} exited without reporting "
                    f"(exit codes {[procs[r].poll() for r in dead]})")
            raise LaunchError(
                f"timed out after {run_timeout}s: only {len(results)}/{np} "
                "ranks reported")
        return [results[r].payload for r in range(np)]
    finally:
        _kill_all(procs)
        driver.close()


__all__ = ["run", "launch_command", "launch_job", "JobResult",
           "WorkerExit", "classify_exit", "LaunchError",
           "spawn_worker", "spawn_worker_ssh", "kill_worker",
           "terminate_worker",
           "EXIT_CLEAN", "EXIT_PREEMPTED", "EXIT_RESIZED", "EXIT_USAGE"]
