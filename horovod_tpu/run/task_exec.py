"""Per-rank exec stub for ``horovod_tpu.run.run(fn, ...)``.

Role parity with reference horovod/spark/task/mpirun_exec_fn.py:29-48:
look up identity from the environment, fetch the pickled fn from the
driver, run it, report the result — plus the parent-death watchdog
(reference :25-31) so orphaned ranks exit instead of leaking.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback


def _parent_watchdog(parent_pid: int) -> None:
    while True:
        if os.getppid() != parent_pid:
            os._exit(1)  # launcher died; don't linger
        time.sleep(1.0)


def main() -> int:
    rank = int(os.environ["HOROVOD_RANK"])
    key = bytes.fromhex(os.environ["HOROVOD_SECRET"])

    threading.Thread(target=_parent_watchdog, args=(os.getppid(),),
                     daemon=True).start()

    from horovod_tpu.run.driver import WorkerClient, probe_service

    # HOROVOD_DRIVER carries one or more candidate endpoints (multi-NIC
    # hosts publish every interface); probe for the first reachable one
    # (reference Spark task-side discovery, spark/__init__.py:123-140).
    candidates = os.environ["HOROVOD_DRIVER"].split(",")
    if len(candidates) == 1:
        host, _, port = candidates[0].rpartition(":")
        addr = (host, int(port))
    else:
        addr = probe_service(candidates, key)

    client = WorkerClient(addr, key)
    client.register(rank, os.uname().nodename)
    try:
        # fetch_task can itself fail (e.g. the fn unpickles by reference
        # from a module this worker cannot import) — report that too, so
        # the driver fails fast instead of waiting out its timeout.
        task = client.fetch_task(rank)
        result = task.fn(*task.args, **task.kwargs)
    except BaseException:
        client.report(rank, False, traceback.format_exc())
        return 1
    client.report(rank, True, result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
