"""``hvdrun`` console entry: ``hvdrun -np N [-H hosts] cmd args...``

The ``horovodrun`` analogue (the reference's documented launch was
``mpirun -np N python train.py``, docs/running.md); this launcher owns
placement and the Horovod environment itself — no MPI runtime. The
``python -m horovod_tpu.run`` form (``__main__.py``) is the same CLI.
"""

from __future__ import annotations

import argparse
import sys

from horovod_tpu.run import launch_command


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch an N-rank horovod_tpu job.")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of ranks")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host1:slots,host2:slots (default: all local)")
    parser.add_argument("--jax", action="store_true", dest="jax_distributed",
                        help="join workers into ONE global jax device mesh "
                             "(sets HOROVOD_JAX_COORDINATOR; each worker's "
                             "hvd.init() then spans all workers' chips)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    cmd = args.command[1:] if args.command[0] == "--" else args.command
    return launch_command(cmd, np=args.num_proc, hosts=args.hosts,
                          jax_distributed=args.jax_distributed)


if __name__ == "__main__":
    sys.exit(main())
