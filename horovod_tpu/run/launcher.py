"""``hvdrun`` console entry: ``hvdrun -np N [-H hosts] cmd args...``

The ``horovodrun`` analogue (the reference's documented launch was
``mpirun -np N python train.py``, docs/running.md); this launcher owns
placement and the Horovod environment itself — no MPI runtime. The
``python -m horovod_tpu.run`` form (``__main__.py``) is the same CLI.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from horovod_tpu.run import launch_command


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch an N-rank horovod_tpu job.")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of ranks")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host1:slots,host2:slots (default: all local)")
    parser.add_argument("--jax", action="store_true", dest="jax_distributed",
                        help="join workers into ONE global jax device mesh "
                             "(sets HOROVOD_JAX_COORDINATOR; each worker's "
                             "hvd.init() then spans all workers' chips)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the whole job up to N times after a "
                             "failure. Combined with the checkpoint/resume "
                             "pattern (rank-0 checkpoint + re-broadcast, "
                             "flax.CheckpointCallback) the relaunch resumes "
                             "from the last saved step. 0 = fail fast, the "
                             "reference's MPI semantics")
    parser.add_argument("--elastic", action="store_true",
                        help="preemption-tolerant supervision "
                             "(horovod_tpu.elastic): classify each "
                             "worker exit (clean / usage / preempted / "
                             "resized / stalled / crashed), tear down "
                             "the world and relaunch; workers resume "
                             "from the latest snapshot manifest "
                             "(elastic.run_elastic / Snapshotter). "
                             "Preemptions (exit 75 or SIGTERM) and "
                             "resizes (exit 76) relaunch for free; "
                             "crashes and stalls consume the "
                             "--max-restarts budget")
    parser.add_argument("--max-restarts", type=int, default=1,
                        help="crash-restart budget for --elastic "
                             "(default 1; preemptions don't consume it)")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic world floor: a preemption "
                             "relaunches at the surviving rank count "
                             "(>= this) instead of retrying full size; "
                             "workers reshard-resume through the "
                             "manifest cursor remap (default: -np, a "
                             "fixed world)")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic world ceiling for regrowth "
                             "(default: -np)")
    parser.add_argument("--slots-file", default=None,
                        help="path to a file holding the currently "
                             "available worker-slot count (kept current "
                             "by the fleet scheduler/agent); each "
                             "relaunch clamps the world to min(slots, "
                             "--max-np), so a shrunken job grows back "
                             "when capacity returns")
    parser.add_argument("--watchdog-timeout", type=float, default=None,
                        help="health-watchdog deadline in seconds: a "
                             "rank whose heartbeat (touched every "
                             "window boundary) goes stale past this is "
                             "killed, classified 'stalled' and the job "
                             "relaunched (default: "
                             "HOROVOD_WATCHDOG_TIMEOUT or 300; 0 "
                             "disables)")
    parser.add_argument("--metrics-file", default=None,
                        help="append one PERF_RUNS.tsv-format JSON line "
                             "of recovery metrics (restarts by class, "
                             "world trajectory, time-to-detect/"
                             "relaunch) at job end; rendered by "
                             "tools/perf_summary.py's elastic column")
    parser.add_argument("--fault-plan", default=None,
                        help="deterministic fault injection plan, e.g. "
                             "'kill:rank=1,step=7;resize:rank=0,step=9,"
                             "n=1' — validated here, exported to "
                             "workers as HOROVOD_FAULT_PLAN (grammar: "
                             "docs/elastic.md)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    if args.max_restarts < 0:
        parser.error("--max-restarts must be >= 0")
    if args.restarts and args.elastic:
        parser.error("--restarts and --elastic are mutually exclusive "
                     "(--elastic already relaunches; use --max-restarts)")
    for flag in ("min_np", "max_np", "slots_file", "watchdog_timeout",
                 "metrics_file"):
        if getattr(args, flag) is not None and not args.elastic:
            parser.error(f"--{flag.replace('_', '-')} requires --elastic")
    min_np = args.min_np if args.min_np is not None else args.num_proc
    max_np = args.max_np if args.max_np is not None else args.num_proc
    if args.elastic and not 1 <= min_np <= args.num_proc <= max_np:
        parser.error(f"need 1 <= --min-np ({min_np}) <= -np "
                     f"({args.num_proc}) <= --max-np ({max_np})")
    env = None
    if args.fault_plan is not None:
        # Validate the grammar HERE so a typo'd plan is a usage error at
        # launch, not a silently-injecting-nothing "green" run.
        from horovod_tpu.elastic.faults import FaultPlanError, \
            parse_fault_plan

        try:
            plan = parse_fault_plan(args.fault_plan)
        except FaultPlanError as e:
            parser.error(str(e))
        if any(a.kind == "resize" for a in plan) and not args.elastic:
            parser.error("resize: fault actions need --elastic (the "
                         "supervisor is what relaunches at the new "
                         "world size)")
        for a in plan:
            if a.kind == "resize" and not min_np <= a.n <= max_np:
                parser.error(
                    f"fault plan resize n={a.n} is outside the elastic "
                    f"world bounds [{min_np}, {max_np}]; widen "
                    "--min-np/--max-np or fix the plan")
        env = dict(os.environ)
        env["HOROVOD_FAULT_PLAN"] = args.fault_plan
    cmd = args.command[1:] if args.command[0] == "--" else args.command
    if args.elastic:
        from horovod_tpu.elastic.supervisor import (slots_file_capacity,
                                                    supervise)

        capacity_fn = (slots_file_capacity(args.slots_file)
                       if args.slots_file else None)
        return supervise(cmd, np=args.num_proc, hosts=args.hosts,
                         env=env, jax_distributed=args.jax_distributed,
                         max_restarts=args.max_restarts,
                         restart_delay=3.0 if args.hosts else 0.0,
                         min_np=min_np, max_np=max_np,
                         capacity_fn=capacity_fn,
                         watchdog_timeout=args.watchdog_timeout,
                         metrics_path=args.metrics_file)
    for attempt in range(args.restarts + 1):
        rc = launch_command(cmd, np=args.num_proc, hosts=args.hosts,
                            env=env,
                            jax_distributed=args.jax_distributed)
        if rc == 0:
            return 0
        if rc == 2:
            # Exit code 2 is the Unix/argparse usage-error convention:
            # bad CLI flags or import-time misuse rerun identically, so
            # burning the restart budget on them only delays the real
            # error reaching the user.
            print("hvdrun: exit code 2 (usage error) — deterministic "
                  "failure, not relaunching", file=sys.stderr, flush=True)
            return rc
        if attempt < args.restarts:
            print(f"hvdrun: attempt {attempt + 1} failed (exit {rc}); "
                  f"relaunching ({args.restarts - attempt} restart(s) "
                  f"left)", file=sys.stderr, flush=True)
            # Local workers are reaped by _kill_all before launch_command
            # returns; ssh-remote teardown is asynchronous (pty HUP), so
            # give it a moment before the relaunch contends for devices.
            if args.hosts:
                time.sleep(3.0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
