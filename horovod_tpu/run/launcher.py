"""``hvdrun`` console entry: ``hvdrun -np N [-H hosts] cmd args...``

The ``horovodrun`` analogue (the reference's documented launch was
``mpirun -np N python train.py``, docs/running.md); this launcher owns
placement and the Horovod environment itself — no MPI runtime. The
``python -m horovod_tpu.run`` form (``__main__.py``) is the same CLI.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from horovod_tpu.run import launch_command


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch an N-rank horovod_tpu job.")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of ranks")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host1:slots,host2:slots (default: all local)")
    parser.add_argument("--jax", action="store_true", dest="jax_distributed",
                        help="join workers into ONE global jax device mesh "
                             "(sets HOROVOD_JAX_COORDINATOR; each worker's "
                             "hvd.init() then spans all workers' chips)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the whole job up to N times after a "
                             "failure. Combined with the checkpoint/resume "
                             "pattern (rank-0 checkpoint + re-broadcast, "
                             "flax.CheckpointCallback) the relaunch resumes "
                             "from the last saved step. 0 = fail fast, the "
                             "reference's MPI semantics")
    parser.add_argument("--elastic", action="store_true",
                        help="preemption-tolerant supervision "
                             "(horovod_tpu.elastic): classify each "
                             "worker exit (clean / usage / preempted / "
                             "crashed), tear down the world and relaunch "
                             "all ranks; workers resume from the latest "
                             "snapshot manifest (elastic.run_elastic / "
                             "Snapshotter). Preemptions (exit 75 or "
                             "SIGTERM) relaunch for free; crashes consume "
                             "the --max-restarts budget")
    parser.add_argument("--max-restarts", type=int, default=1,
                        help="crash-restart budget for --elastic "
                             "(default 1; preemptions don't consume it)")
    parser.add_argument("--fault-plan", default=None,
                        help="deterministic fault injection plan, e.g. "
                             "'kill:rank=1,step=7;stall:rank=2,step=12' "
                             "— validated here, exported to workers as "
                             "HOROVOD_FAULT_PLAN (grammar: "
                             "docs/elastic.md)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    if args.max_restarts < 0:
        parser.error("--max-restarts must be >= 0")
    if args.restarts and args.elastic:
        parser.error("--restarts and --elastic are mutually exclusive "
                     "(--elastic already relaunches; use --max-restarts)")
    env = None
    if args.fault_plan is not None:
        # Validate the grammar HERE so a typo'd plan is a usage error at
        # launch, not a silently-injecting-nothing "green" run.
        from horovod_tpu.elastic.faults import FaultPlanError, \
            parse_fault_plan

        try:
            parse_fault_plan(args.fault_plan)
        except FaultPlanError as e:
            parser.error(str(e))
        env = dict(os.environ)
        env["HOROVOD_FAULT_PLAN"] = args.fault_plan
    cmd = args.command[1:] if args.command[0] == "--" else args.command
    if args.elastic:
        from horovod_tpu.elastic.supervisor import supervise

        return supervise(cmd, np=args.num_proc, hosts=args.hosts,
                         env=env, jax_distributed=args.jax_distributed,
                         max_restarts=args.max_restarts,
                         restart_delay=3.0 if args.hosts else 0.0)
    for attempt in range(args.restarts + 1):
        rc = launch_command(cmd, np=args.num_proc, hosts=args.hosts,
                            env=env,
                            jax_distributed=args.jax_distributed)
        if rc == 0:
            return 0
        if rc == 2:
            # Exit code 2 is the Unix/argparse usage-error convention:
            # bad CLI flags or import-time misuse rerun identically, so
            # burning the restart budget on them only delays the real
            # error reaching the user.
            print("hvdrun: exit code 2 (usage error) — deterministic "
                  "failure, not relaunching", file=sys.stderr, flush=True)
            return rc
        if attempt < args.restarts:
            print(f"hvdrun: attempt {attempt + 1} failed (exit {rc}); "
                  f"relaunching ({args.restarts - attempt} restart(s) "
                  f"left)", file=sys.stderr, flush=True)
            # Local workers are reaped by _kill_all before launch_command
            # returns; ssh-remote teardown is asynchronous (pty HUP), so
            # give it a moment before the relaunch contends for devices.
            if args.hosts:
                time.sleep(3.0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
