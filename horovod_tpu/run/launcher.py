"""``hvdrun`` console entry: ``hvdrun -np N [-H hosts] cmd args...``

The ``horovodrun`` analogue (the reference's documented launch was
``mpirun -np N python train.py``, docs/running.md); this launcher owns
placement and the Horovod environment itself — no MPI runtime. The
``python -m horovod_tpu.run`` form (``__main__.py``) is the same CLI.
"""

from __future__ import annotations

import argparse
import sys
import time

from horovod_tpu.run import launch_command


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch an N-rank horovod_tpu job.")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of ranks")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host1:slots,host2:slots (default: all local)")
    parser.add_argument("--jax", action="store_true", dest="jax_distributed",
                        help="join workers into ONE global jax device mesh "
                             "(sets HOROVOD_JAX_COORDINATOR; each worker's "
                             "hvd.init() then spans all workers' chips)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the whole job up to N times after a "
                             "failure. Combined with the checkpoint/resume "
                             "pattern (rank-0 checkpoint + re-broadcast, "
                             "flax.CheckpointCallback) the relaunch resumes "
                             "from the last saved step. 0 = fail fast, the "
                             "reference's MPI semantics")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    cmd = args.command[1:] if args.command[0] == "--" else args.command
    for attempt in range(args.restarts + 1):
        rc = launch_command(cmd, np=args.num_proc, hosts=args.hosts,
                            jax_distributed=args.jax_distributed)
        if rc == 0:
            return 0
        if rc == 2:
            # Exit code 2 is the Unix/argparse usage-error convention:
            # bad CLI flags or import-time misuse rerun identically, so
            # burning the restart budget on them only delays the real
            # error reaching the user.
            print("hvdrun: exit code 2 (usage error) — deterministic "
                  "failure, not relaunching", file=sys.stderr, flush=True)
            return rc
        if attempt < args.restarts:
            print(f"hvdrun: attempt {attempt + 1} failed (exit {rc}); "
                  f"relaunching ({args.restarts - attempt} restart(s) "
                  f"left)", file=sys.stderr, flush=True)
            # Local workers are reaped by _kill_all before launch_command
            # returns; ssh-remote teardown is asynchronous (pty HUP), so
            # give it a moment before the relaunch contends for devices.
            if args.hosts:
                time.sleep(3.0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
