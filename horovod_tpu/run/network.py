"""TCP RPC with HMAC-SHA256-authenticated cloudpickle wire format.

Role parity with reference horovod/spark/util/network.py (BasicService /
BasicClient over ThreadingTCPServer, ``Wire`` integrity layer :43-76) and
util/secret.py (32-byte keys + digest check :21-36). The rebuild's
launcher uses it for worker registration, address exchange, function
distribution and result collection — the same jobs the Spark orchestrator
did around mpirun (SURVEY §2.8), minus Spark.

Security model (same as the reference): pickle over the network is only
accepted when authenticated by the job's ephemeral shared secret, which
never leaves the launcher's process tree (passed via environment).
"""

from __future__ import annotations

import hashlib
import hmac
import secrets as _secrets
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Optional

import cloudpickle

DIGEST_LEN = hashlib.sha256().digest_size
MAX_FRAME = 1 << 30


def make_secret_key() -> bytes:
    """32 random bytes (reference secret.py:21-26)."""
    return _secrets.token_bytes(32)


def _route_probe_ip():
    """The default-route interface's IP via the UDP-connect trick (no
    packet is sent — ``connect`` on a datagram socket only selects the
    route). Returns None instead of raising: on an air-gapped or
    offline host the kernel has no route to 8.8.8.8 and ``connect``
    raises ``OSError`` (ENETUNREACH) — that must degrade to the next
    resolution rung, never kill address discovery."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))  # no traffic: picks the route only
            return s.getsockname()[0] or None
    except OSError:
        return None


def _hostname_ips():
    """Every IPv4 address the hostname resolves to ([] when resolution
    fails — a bare container with no /etc/hosts entry)."""
    try:
        return [info[4][0]
                for info in socket.getaddrinfo(socket.gethostname(), None,
                                               socket.AF_INET)]
    except (socket.gaierror, OSError):
        return []


def advertise_ip() -> str:
    """The single best address to ADVERTISE a locally-bound service at,
    with the offline-host fallback chain: default-route interface (the
    UDP-connect probe) -> hostname resolution (first non-loopback
    address) -> loopback. Never raises — an air-gapped host where the
    route probe gets ``OSError`` still resolves (the serving fleet's
    TCP workers print their advertised endpoint through this)."""
    ip = _route_probe_ip()
    if ip:
        return ip
    for ip in _hostname_ips():
        if ip and not ip.startswith("127."):
            return ip
    return "127.0.0.1"


def candidate_addresses(port: int) -> list:
    """Every plausible ``host:port`` endpoint a service bound on 0.0.0.0
    of this machine can be reached at: loopback, the hostname's
    addresses, and the default-route interface (UDP-connect trick — no
    packet is sent; degrades through :func:`advertise_ip`'s fallback
    chain on offline hosts). The reference's Spark driver enumerated
    NICs the same way and let tasks probe for the routable subset
    (spark/__init__.py:33-39,123-140); on a multi-NIC pod only some of
    these are reachable from a given worker, so publish them ALL and let
    the worker probe (:func:`horovod_tpu.run.driver.probe_service`)."""
    ips = ["127.0.0.1"]

    def add(ip: str) -> None:
        if ip and ip not in ips:
            ips.append(ip)

    for ip in _hostname_ips():
        add(ip)
    add(advertise_ip())
    return [f"{ip}:{port}" for ip in ips]


class IntegrityError(RuntimeError):
    pass


class Wire:
    """Length-prefixed frames: [u64 len][HMAC-SHA256][payload]."""

    def __init__(self, key: bytes):
        self._key = key

    def write(self, sock: socket.socket, obj: Any) -> None:
        payload = cloudpickle.dumps(obj)
        digest = hmac.new(self._key, payload, hashlib.sha256).digest()
        sock.sendall(struct.pack("<Q", len(payload)) + digest + payload)

    def read(self, sock: socket.socket,
             timeout: Optional[float] = None) -> Any:
        """Read one authenticated frame. ``timeout`` bounds time
        WITHOUT PROGRESS — a peer that stops mid-frame raises
        ``socket.timeout`` instead of hanging the reader forever (the
        HVD011 shape), while a large frame (MAX_FRAME is 1 GiB —
        cloudpickled functions and results ride this wire) that keeps
        trickling within the budget still completes."""
        header = self._read_exact(sock, 8 + DIGEST_LEN, timeout)
        (length,) = struct.unpack("<Q", header[:8])
        if length > MAX_FRAME:
            raise IntegrityError("oversized frame")
        digest = header[8:]
        payload = self._read_exact(sock, length, timeout)
        expected = hmac.new(self._key, payload, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            # Never unpickle unauthenticated bytes (reference
            # network.py:69-75 raises the same way).
            raise IntegrityError("message integrity check failed")
        return cloudpickle.loads(payload)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int,
                    timeout: Optional[float] = None) -> bytes:
        """``timeout`` is a no-progress bound: the deadline re-arms on
        every received chunk, so only a STALLED peer trips it — never
        a slow link moving a legitimately large frame."""
        buf = b""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout(
                        f"no progress for {timeout:g}s after "
                        f"{len(buf)}/{n} frame bytes")
                sock.settimeout(remaining)
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed connection")
            buf += chunk
            if deadline is not None:
                deadline = time.monotonic() + timeout
        return buf


class BasicService:
    """Threaded TCP request/response server: one authenticated request
    object in, one response object out, dispatched to ``handle``."""

    def __init__(self, name: str, key: bytes,
                 handler: Callable[[Any], Any]):
        self._name = name
        self._wire = Wire(key)
        self._handler = handler
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    # Bounded: a half-open client that never finishes
                    # its frame must release this handler thread, not
                    # hold it forever.
                    req = outer._wire.read(self.request, timeout=60.0)
                except (IntegrityError, ConnectionError, socket.timeout):
                    return  # drop unauthenticated/broken connections
                try:
                    resp = outer._handler(req)
                except Exception as e:  # surfaced to the client
                    resp = RemoteError(repr(e))
                try:
                    outer._wire.write(self.request, resp)
                except (ConnectionError, OSError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"{name}-service")
        self._thread.start()

    @property
    def addr(self):
        host, port = self._server.server_address
        return host, port

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RemoteError:
    def __init__(self, message: str):
        self.message = message


class BasicClient:
    """One request/response round trip per call."""

    def __init__(self, addr, key: bytes, timeout: float = 60.0):
        self._addr = tuple(addr)
        self._wire = Wire(key)
        self._timeout = timeout

    def request(self, obj: Any) -> Any:
        with socket.create_connection(self._addr,
                                      timeout=self._timeout) as sock:
            self._wire.write(sock, obj)
            # The connection timeout bounds each recv(); the explicit
            # frame timeout bounds the WHOLE reply (a trickling peer
            # resets per-recv timeouts forever otherwise).
            resp = self._wire.read(sock, timeout=self._timeout)
        if isinstance(resp, RemoteError):
            raise RuntimeError(f"remote error: {resp.message}")
        return resp
