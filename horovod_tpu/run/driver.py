"""Launch driver: rendezvous, function distribution, result collection.

Role parity with reference horovod/spark/driver/driver_service.py (task
registration, address table, code distribution :21-95) and
horovod/spark/task/* (fetch fn, run, report result) — with the process
placement the Spark+mpirun stack did (SURVEY §2.8) done directly by the
launcher (subprocess locally, ssh per host remotely).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Optional

from horovod_tpu.run.network import BasicClient, BasicService


@dataclasses.dataclass
class RegisterRequest:
    rank: int
    host: str


@dataclasses.dataclass
class RegisterResponse:
    ok: bool


@dataclasses.dataclass
class FetchTaskRequest:
    rank: int


@dataclasses.dataclass
class FetchTaskResponse:
    fn: Any          # cloudpickled-by-wire callable
    args: tuple
    kwargs: dict


@dataclasses.dataclass
class ResultRequest:
    rank: int
    success: bool
    payload: Any     # return value or formatted traceback


@dataclasses.dataclass
class Ack:
    pass


@dataclasses.dataclass
class Ping:
    """Reachability probe (multi-NIC discovery): any authenticated
    endpoint of the driver answers, proving the address routes AND the
    peer holds the job secret (an open port alone is not enough)."""


def probe_service(addrs, key: bytes, timeout: float = 1.5):
    """First address in ``addrs`` (each ``\"host:port\"``) that answers an
    authenticated :class:`Ping`, as a ``(host, port)`` tuple.

    The reference's Spark tasks probed the driver's candidate interfaces
    and kept the routable intersection (spark/__init__.py:123-140); here
    a worker runs the probe once before registering. Raises
    ``ConnectionError`` listing the candidates when none answers."""
    tried = []
    for addr in addrs:
        host, _, port = addr.rpartition(":")
        try:
            BasicClient((host, int(port)), key, timeout=timeout).request(
                Ping())
            return host, int(port)
        except Exception as e:  # unroutable, refused, timeout, bad auth
            tried.append(f"{addr} ({type(e).__name__})")
    raise ConnectionError(
        "no driver endpoint reachable; tried: " + ", ".join(tried))


# --------------------------------------------------------------- exit codes
# Per-worker exit taxonomy, the contract between workers, the launcher's
# supervision loop and the elastic supervisor (horovod_tpu/elastic/
# supervisor.py). The reference collapsed every failure into mpirun's
# opaque kill-all; propagating the class lets `hvdrun --elastic` decide
# relaunch-vs-fail-fast per incident.

#: Clean completion.
EXIT_CLEAN = 0
#: argparse/usage convention: deterministic, reruns identically — a
#: restart budget must never be burned on these.
EXIT_USAGE = 2
#: Preempted: the worker received SIGTERM (TPU maintenance event, spot
#: reclaim), drained, wrote its final snapshot and exited on purpose.
#: 75 = EX_TEMPFAIL from sysexits.h — "transient, retry later".
EXIT_PREEMPTED = 75
#: Resized: the worker drained, wrote its final snapshot and exited on
#: purpose to request a world resize (the ``resize:`` fault action, or
#: an external scheduler asking the job to change shape). The elastic
#: supervisor relaunches at the requested world size without consuming
#: the restart budget — an orchestrated resize is not a failure.
EXIT_RESIZED = 76


def classify_exit(code) -> str:
    """Map a worker exit code to ``clean|usage|preempted|resized|crashed``.

    Negative codes are subprocess ``-signum`` deaths: ``-SIGTERM`` is
    classed *preempted* (the cluster reclaimed the worker before the
    in-process handler could convert it to :data:`EXIT_PREEMPTED` — same
    recovery either way), every other signal (SIGKILL = OOM-kill or
    fault-injected crash, SIGSEGV, ...) is *crashed*. A sixth category,
    *stalled*, cannot be derived from the code alone — the health
    watchdog marks it on the :class:`WorkerExit` when IT was the one
    that killed the silent worker.
    """
    import signal as _signal

    if code == EXIT_CLEAN:
        return "clean"
    if code == EXIT_USAGE:
        return "usage"
    if code == EXIT_PREEMPTED or code == -_signal.SIGTERM:
        return "preempted"
    if code == EXIT_RESIZED:
        return "resized"
    return "crashed"


@dataclasses.dataclass
class WorkerExit:
    """One worker's observed exit: rank, raw code, classified category.

    ``stalled`` is set by the launcher when the supervisor's health
    watchdog killed this worker for a stale heartbeat — the raw code is
    then the watchdog's SIGKILL, and the *category* reports ``stalled``
    so the relaunch policy and recovery metrics see the real incident
    class, not a generic crash.

    The taxonomy is deliberately process-agnostic: the serving fleet
    (:mod:`horovod_tpu.serve.fleet`) classifies replica incidents with
    the same class — ``rank`` is then the replica id — so training and
    serving recovery metrics speak one vocabulary."""

    rank: int
    code: int
    stalled: bool = False

    @property
    def category(self) -> str:
        if self.stalled:
            return "stalled"
        return classify_exit(self.code)

    def describe(self, role: str = "rank") -> str:
        """One-line incident description for supervisor/fleet logs,
        e.g. ``"replica 1 exited -9 (crashed)"``."""
        return f"{role} {self.rank} exited {self.code} ({self.category})"


class Driver:
    """Runs in the launcher process; workers talk to it over the
    authenticated RPC."""

    def __init__(self, world_size: int, key: bytes, fn=None, args=(),
                 kwargs=None):
        self._world_size = world_size
        self._fn = fn
        self._args = args
        self._kwargs = kwargs or {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._registered: Dict[int, str] = {}
        self._results: Dict[int, ResultRequest] = {}
        self._service = BasicService("driver", key, self._handle)

    @property
    def port(self) -> int:
        return self._service.port

    def _handle(self, req):
        if isinstance(req, Ping):
            return Ack()
        if isinstance(req, RegisterRequest):
            with self._cond:
                self._registered[req.rank] = req.host
                self._cond.notify_all()
            return RegisterResponse(ok=True)
        if isinstance(req, FetchTaskRequest):
            return FetchTaskResponse(fn=self._fn, args=self._args,
                                     kwargs=self._kwargs)
        if isinstance(req, ResultRequest):
            with self._cond:
                self._results[req.rank] = req
                self._cond.notify_all()
            return Ack()
        raise ValueError(f"unknown request {type(req).__name__}")

    def wait_registered(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self._registered) < self._world_size:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cond.wait(remain)
        return True

    def wait_results(self, timeout: float,
                     should_abort=None) -> Dict[int, ResultRequest]:
        """``should_abort()`` lets the launcher bail when a worker process
        dies without reporting (crash, OOM-kill)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self._results) < self._world_size:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                # Fast failure propagation (reference spark/__init__.py:
                # 181-192): one failed rank fails the job immediately.
                if any(not r.success for r in self._results.values()):
                    break
                if should_abort is not None and should_abort():
                    break
                self._cond.wait(min(remain, 0.25))
            return dict(self._results)

    def close(self) -> None:
        self._service.close()


class WorkerClient:
    """Worker-side RPC stub (reference task_service.py role)."""

    def __init__(self, driver_addr, key: bytes):
        self._client = BasicClient(driver_addr, key)

    def register(self, rank: int, host: str) -> None:
        self._client.request(RegisterRequest(rank=rank, host=host))

    def fetch_task(self, rank: int) -> FetchTaskResponse:
        return self._client.request(FetchTaskRequest(rank=rank))

    def report(self, rank: int, success: bool, payload: Any) -> None:
        self._client.request(ResultRequest(rank=rank, success=success,
                                           payload=payload))
