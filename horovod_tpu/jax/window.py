"""On-device multi-step training windows: amortize host dispatch.

PERF.md's round-5 honest profiles attribute a 27-32% host-side gap on
short-step models (ResNet-50: 33.8 ms wall vs 24.8 ms device; Inception
V3: 32%) to per-step Python dispatch plus the tunnel's fixed ~65 ms
sync tax per synced window. The structural fix is the same host/device
decoupling the reference got from its background coordinator thread
(``BackgroundThreadLoop``: the training script never blocks on the
exchange) — in XLA form: compile K training steps into ONE program with
``lax.scan``, so the host dispatches once per window and syncs once per
window instead of once per step. This is the standard JAX-on-TPU
training-loop idiom (the scan-based step loops in T5X/MaxText-class
trainers). Measured lever (PERF.md round 5): 30-step windows alone
lifted ResNet-50 +22% to 2,320 img/s against a ~2,580 img/s
device-only ceiling.

Two layers:

* :func:`windowed` — the pure transform: ``step_fn`` -> a window step
  that scans K stacked batches through it, carrying the train state and
  accumulating metric MEANS on device (one small transfer per window,
  not K).
* :func:`run_steps` — the full loop: stages K-batch windows onto the
  device double-buffered (:func:`horovod_tpu.data.prefetch_windows`, so
  host->device copies of window N+1 overlap compute of window N),
  dispatches one compiled window per K batches with the train state
  donated, and marks window boundaries on the Horovod timeline.

Numerical contract (pinned in tests/test_window.py): a K-step window is
allclose-equivalent to K sequential calls of the same ``step_fn`` —
same RNG folding (the per-step dropout key derives from the carried
``state["step"]``, which the scan advances exactly as sequential calls
do), same parameter/optimizer trajectories, same metric means.
"""

from __future__ import annotations

import functools
from typing import Any, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.logical import module_axis


def windowed(step_fn, steps_per_dispatch: int):
    """Compile ``steps_per_dispatch`` applications of ``step_fn`` into
    one scanned window step.

    ``step_fn`` must have the training-step signature
    ``(state, batch) -> (new_state, metrics)``. The returned function
    takes ``(state, stacked_batches)`` where every batch leaf carries a
    leading window axis of length K, scans the K steps on device, and
    returns ``(final_state, metric_means)`` — metrics averaged over the
    window on device, so the host sees one small result per window.

    ``steps_per_dispatch == 1`` returns ``step_fn`` unchanged (the
    identity path: no window axis, no scan, bit-identical dispatch).
    """
    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    if k == 1:
        return step_fn
    return _scan_window(step_fn)


def _scan_window(step_fn):
    """The scan form itself: shape-polymorphic in the window length (the
    scan length comes from the stacked input's leading axis, so one
    handle serves full windows and a shorter trailing window alike —
    jit retraces per distinct length)."""

    @functools.wraps(step_fn)
    def window_step(state, stacked_batches):
        state, stacked_metrics = jax.lax.scan(
            lambda carry, batch: step_fn(carry, batch),
            state, stacked_batches)
        metrics = jax.tree_util.tree_map(
            lambda m: jnp.mean(m, axis=0), stacked_metrics)
        return state, metrics

    return window_step


def stack_batches(batches: Iterable):
    """Stack a list of batch pytrees along a new leading window axis
    (device-side ``jnp.stack``; for the host-side double-buffered stager
    use :func:`horovod_tpu.data.prefetch_windows`)."""
    batches = list(batches)
    if not batches:
        raise ValueError("stack_batches needs at least one batch")
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *batches)


def repeat_batch(batch, steps_per_dispatch: int):
    """Synthetic-bench staging: one batch broadcast under a K-long
    window axis without K host copies (``bench.py`` reuses the same
    synthetic batch every step, so the window lane stages one broadcast
    instead of K stacked duplicates)."""
    k = int(steps_per_dispatch)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), batch)


def stage_synthetic_window(step_fn, batch, steps_per_dispatch: int,
                           batch_specs: Any = None):
    """Synthetic-benchmark window staging, in one place for every timing
    harness (bench.py, tools/profile_step.py): wrap the step in the scan
    window, broadcast the single reusable batch under the K-long window
    axis, and shift the batch partition specs to the stacked layout.
    Returns ``(step_fn, batch, batch_specs)``; K=1 is the identity
    triple — the reference protocol's per-step dispatch, untouched.
    ``batch_specs=None`` shards the batch over the data axis resolved
    through the bound LogicalMesh (legacy ``"hvd"`` when none is
    bound)."""
    if batch_specs is None:
        batch_specs = P(module_axis("data"))
    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    if k == 1:
        return step_fn, batch, batch_specs
    return (_scan_window(step_fn), repeat_batch(batch, k),
            stacked_specs(batch_specs))


def stacked_specs(batch_specs):
    """Shift batch partition specs under the window axis:
    ``P("hvd") -> P(None, "hvd")`` per leaf — the scan axis is
    replicated (every rank walks the same K steps), the batch sharding
    moves to axis 1."""
    return jax.tree_util.tree_map(
        lambda spec: P(None, *spec), batch_specs,
        is_leaf=lambda x: isinstance(x, P))


def run_steps(
    step_fn,
    state,
    batches: Iterable,
    steps_per_dispatch: int = 1,
    *,
    mesh=None,
    axis_name: Optional[str] = None,
    state_specs: Any = P(),
    batch_specs: Any = None,
    metric_specs: Any = P(),
    donate: bool = True,
    prefetch: int = 2,
    sync_each_window: bool = False,
) -> Tuple[Any, List[Any]]:
    """Run ``step_fn`` over ``batches`` in K-step on-device windows.

    The training-loop entry of the window API::

        state, window_metrics = hvd.run_steps(
            train_step, state, batch_iter, steps_per_dispatch=30)

    Per window of K consecutive batches: the batches are stacked on the
    host and staged to the device double-buffered (the stager keeps
    ``prefetch`` windows in flight, so window N+1's host->device copy
    overlaps window N's compute), then ONE jitted+sharded
    ``lax.scan``-of-K-steps program is dispatched with the train state
    donated — one dispatch per window instead of K, which is what
    closes the measured per-step host-dispatch gap (PERF.md round 5).

    Returns ``(final_state, metrics)`` where ``metrics`` is one pytree
    per window: the on-device metric MEANS over that window's steps
    (with ``steps_per_dispatch == 1``, the raw per-step metrics — the
    identity path, equivalent to calling ``spmd_fn(step_fn)`` in a
    plain Python loop).

    A trailing window shorter than K (when ``len(batches)`` is not a
    multiple of K) runs as a shorter scan — every batch trains, at the
    cost of one extra compile for the tail length.

    ``sync_each_window`` forces a real device sync (and a timeline
    ``WINDOW_SYNC`` span) at every window boundary — for timing
    harnesses; training loops should leave it False and let dispatch
    pipeline across windows.
    """
    from horovod_tpu.common import state as _state
    from horovod_tpu.data.prefetch import prefetch_windows
    from horovod_tpu.parallel.spmd import spmd_fn
    from horovod_tpu.utils import timeline as _tl
    from horovod_tpu.utils.devsync import window_sync

    axis_name = module_axis("data", axis_name)
    if batch_specs is None:
        batch_specs = P(axis_name)
    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    st = _state.global_state()
    if mesh is None:
        st.require_init()
        mesh = st.mesh
    tl = getattr(st, "timeline", None)
    tl_on = tl is not None and tl.enabled

    # Single-spec batch trees ride the stager straight to their mesh
    # layout; pytree-of-specs batches fall back to plain device_put and
    # the dispatch reshards on entry.
    window_batch_specs = stacked_specs(batch_specs) if k > 1 else batch_specs
    sharding = (NamedSharding(mesh, window_batch_specs)
                if isinstance(window_batch_specs, P) else None)

    # ONE dispatch handle per loop: the scan form is shape-polymorphic
    # in the window length, so a trailing window shorter than K rides
    # the same handle (jit retraces once for the tail length — the one
    # extra compile the docstring prices in).
    run = spmd_fn(
        _scan_window(step_fn) if k > 1 else step_fn,
        mesh=mesh,
        axis_name=axis_name,
        in_specs=(state_specs, window_batch_specs),
        out_specs=(state_specs, metric_specs),
        donate_argnums=(0,) if donate else (),
    )

    metrics_out: List[Any] = []
    index = 0
    for window in prefetch_windows(batches, k, size=prefetch,
                                   sharding=sharding):
        length = (1 if k == 1
                  else jax.tree_util.tree_leaves(window)[0].shape[0])
        if tl_on:
            tl.mark_window(index, length)
            tl.start("hvd.window", _tl.WINDOW,
                     args={"window": index, "steps": length,
                           "span": "host_dispatch"})
        try:
            state, metrics = run(state, window)
        finally:
            if tl_on:
                tl.end("hvd.window", _tl.WINDOW)
        if sync_each_window:
            window_sync(state, timeline=tl, steps=length)
        metrics_out.append(metrics)
        index += 1
    return state, metrics_out
