"""Tensor fusion: bucketed flat-buffer collectives, overlap-scheduled.

TPU-native rebuild of the reference's fusion machinery — the 64 MB fusion
buffer (horovod/common/fusion_buffer_manager.h:50-55), the response-merging
look-ahead that packs same-dtype tensors into one collective
(operations.cc:2160-2264), and the MEMCPY_IN/OUT_FUSION_BUFFER data plane
(operations.cc:1491-1586).

Mapping onto XLA:

* the persistent device-side fusion buffer becomes a traced flat
  concatenation — XLA allocates and reuses it across steps;
* "memcpy into the fusion buffer" becomes ``ravel``+``concatenate`` which
  XLA fuses into the collective's prologue;
* one ``lax.psum`` per bucket amortizes ICI latency over many small
  gradients the same way one NCCL launch amortized ring latency;
* bucket boundaries respect HOROVOD_FUSION_THRESHOLD so the env knob (and
  the autotuner that drives it) keeps its meaning.

Overlap scheduling (HOROVOD_OVERLAP=auto|on|off): the reference hid the
gradient exchange behind backward compute by firing an allreduce from each
gradient hook as autograd produced it (Sergeev & Del Balso 2018; PyTorch
DDP's reverse-order buckets, Li et al. VLDB 2020). Under XLA the step is
one program, so the same win is a *scheduling shape* problem: with overlap
on, per-bucket collectives are issued in REVERSE bucket order — the order
backward produces gradients, last layers first — as a start-all/
unpack-later sequence, so each bucket's collective depends only on its own
members and XLA's async collective (start/done) scheduler can slide it
under the remaining backward compute instead of serializing one
post-backward block. Buckets at or above HOROVOD_OVERLAP_SCATTER_THRESHOLD
additionally take the ``psum_scatter`` -> sharded-update -> ``all_gather``
form: identical wire bytes (reduce-scatter + all-gather IS how a ring
allreduce decomposes) and identical numerics, but two independently
schedulable halves — ZeRO-shaped communication with plain-DP semantics
(optimizer state stays replicated; contrast :mod:`horovod_tpu.jax.zero`).
Overlap NEVER changes results: the emission order and collective shape
change, the math does not (pinned bit-exactly in tests/test_overlap.py).

Same-dtype-only fusion matches the reference (it fused only responses with
identical dtype/device signatures, operations.cc:2175-2230).

Hierarchical bucket execution (HOROVOD_HIERARCHICAL=auto|on|off): on a
multi-slice mesh the flat psum would push every gradient byte across
DCN (~3 GB/s/chip) when 200 GB/s ICI sits inside each slice. With the
ladder engaged, each bucket runs intra-slice reduce-scatter -> inter-
slice exchange of the 1/``inner`` shard -> intra-slice all-gather (the
reference's NCCL-within/MPI-across hierarchical allreduce,
operations.cc:1284-1436, as explicit XLA collectives over
``axis_index_groups`` — shared rung: parallel/mesh.py
``hierarchical_ladder_in_axis``; two-level mesh factory:
``hybrid_mesh``). "auto" engages only when the device set spans a DCN
boundary (``parallel.mesh.dcn_present``). Composes with the overlap
schedule (reverse-order issue applies per bucket regardless of its
collective shape); hierarchical buckets never additionally take the
rs+ag scatter form (the ladder already decomposes).

Low-bit DCN wire (``Compression.int8`` / ``Compression.fp8``): the DCN
leg optionally quantizes the shard with a per-bucket absmax scale (the
scale rides beside the payload as a scalar all-gather) and an optional
error-feedback residual carried in optimizer state
(:func:`ef_residual_specs`; Seide et al. 2014 / DGC lineage), so
quantization error is re-injected the next step instead of compounding.
Two exchange shapes: at 2 slices, an all-gather of the quantized shards
with local dequant-sum; at >2 slices, the quantized ring decomposition
— all-to-all of quantized sub-shards, local dequant-sum, re-quantize
(second residual), all-gather — keeping per-chip DCN wire at
``~2(m-1)/m`` of the QUANTIZED shard instead of growing with the slice
count. ICI legs always stay at the bucket's own dtype.

Dtype ladder (where bytes live and where the Average divide happens —
the no-double-scaling contract pinned by tests/test_hierarchical.py):

    compression   ICI wire      DCN wire        accumulate  1/n divide
    ------------  ------------  --------------  ----------  -------------
    none          input dtype   input dtype     input       shard, pre-ag
    fp16 / bf16   wire dtype    wire dtype      wire        tail, fp32*
    int8 / fp8    input dtype   int8/fp8+scale  fp32        shard, pre-ag

    (*) cast compressors divide once, at the decompressed tail — the
    historical flat-path behavior, kept so hierarchical-off and -on
    share one reduction + division sequence exactly. The quantized
    codecs divide the dequantized fp32 shard BEFORE the all-gather
    (elementwise divide commutes with gather — bit-identical to a tail
    divide, 1/inner of the work) and never at the tail, so Average is
    applied exactly once; the error-feedback residual lives in the
    pre-divide SUM domain, so feedback composes with Average without
    double-scaling.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.config import HIERARCHICAL_MODES, OVERLAP_MODES
from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.jax.compression import Compression, is_dcn_wire


def _plan_buckets(sizes_bytes: Sequence[int], threshold: int) -> List[List[int]]:
    """Greedy contiguous bucketing: consecutive tensors pack into a bucket
    until adding the next would exceed ``threshold`` (an oversize tensor
    gets its own bucket, like an oversize response in the reference)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(sizes_bytes):
        if cur and cur_bytes + nb > threshold:
            buckets.append(cur)
            cur = []
            cur_bytes = 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


class Bucket(NamedTuple):
    """One fused-collective bucket of the plan (public accounting record —
    tools/scaling_model.py and the bucket-byte tests consume these)."""

    dtype: str        # wire dtype name, e.g. "float32"
    index: int        # position within this dtype's bucket sequence
    members: tuple    # indices into the input tensor list, input order
    nbytes: int       # payload bytes (sum of member bytes, unpadded)
    oversize: bool    # single tensor alone exceeding the fusion threshold


def _leaf_size(leaf) -> int:
    size = getattr(leaf, "size", None)
    if size is None:  # ShapeDtypeStruct on older jax: derive from shape
        size = int(math.prod(leaf.shape))
    return int(size)


def plan_buckets(leaves, threshold: int) -> List[Bucket]:
    """The full bucket plan for ``leaves`` (arrays or ShapeDtypeStructs):
    grouped by dtype (first-appearance order), greedily packed to
    ``threshold`` bytes within each group, forward (input) order.

    This is exactly the plan :func:`fused_reduce` executes, exposed so the
    scaling model and tests can account bucket bytes without tracing."""
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    plan: List[Bucket] = []
    for dtype, idxs in by_dtype.items():
        sizes = [_leaf_size(leaves[i]) * dtype.itemsize for i in idxs]
        for b, bucket in enumerate(_plan_buckets(sizes, threshold)):
            nbytes = sum(sizes[j] for j in bucket)
            plan.append(Bucket(
                dtype=dtype.name,
                index=b,
                members=tuple(idxs[j] for j in bucket),
                nbytes=nbytes,
                oversize=len(bucket) == 1 and nbytes > threshold,
            ))
    return plan


def plan_summary(plan: Sequence[Bucket]) -> dict:
    """Compact accounting of a bucket plan: the numbers the scaling model
    consumes and bench JSON stamps alongside the overlap knob."""
    total = sum(b.nbytes for b in plan)
    return {
        "count": len(plan),
        "total_bytes": total,
        "total_mb": round(total / (1024 * 1024), 2),
        "oversize_singletons": sum(1 for b in plan if b.oversize),
        "largest_bytes": max((b.nbytes for b in plan), default=0),
    }


def resolve_overlap(mode: Optional[str], n_buckets: int) -> bool:
    """Resolve the overlap knob to a concrete decision for one plan.

    ``auto`` engages overlap emission whenever the plan has >= 2 buckets
    (with a single bucket there is nothing to interleave — the legacy
    single-pass emission is kept so historical wire shapes stay
    byte-identical); ``on`` forces the overlap shape even for one bucket;
    ``off`` is the legacy post-backward block. ``None`` reads the
    HOROVOD_OVERLAP config default.
    """
    if mode is None:
        mode = global_state().config.overlap
    if mode is True:
        mode = "on"
    elif mode is False:
        mode = "off"
    if mode not in OVERLAP_MODES:
        raise InvalidArgumentError(
            f"overlap must be one of {OVERLAP_MODES} (got {mode!r})")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return n_buckets >= 2


def _hierarchical_inner(st, axis_size: int, enabled: bool) -> int:
    """Fast-domain size for the two-level ladder, or 0 when the flat
    collective should be used. Auto mode uses chips-per-process (the
    reference's local/cross comm split, operations.cc:1760-1797).
    (Legacy helper kept for the allgather lane — the allreduce path now
    resolves through :func:`resolve_hierarchical`.)"""
    if not enabled:
        return 0
    inner = st.config.hierarchical_inner_size or st.local_device_count
    if 1 < inner < axis_size and axis_size % inner == 0:
        return inner
    return 0


def resolve_hierarchical(mode: Optional[str], axis_size: int) -> int:
    """Resolve the HOROVOD_HIERARCHICAL knob to a fast-domain (ICI)
    size for this axis, or 0 for the flat collective.

    ``auto`` (default) engages only when the device set spans a DCN
    boundary (multiple slices or processes — ``parallel.mesh.
    dcn_present``), with the detected chips-per-slice as the inner
    size; ``on`` forces the ladder with HOROVOD_HIERARCHICAL_INNER_SIZE
    (falling back to chips-per-slice, then chips-per-process); ``off``
    is the flat collective. The legacy HOROVOD_HIERARCHICAL_ALLREDUCE=1
    boolean reads as ``on``. An inner size that does not strictly
    divide the axis (1 < inner < axis_size) degrades to flat, the
    reference's is_homogeneous degradation (operations.cc:1303-1315).
    """
    st = global_state()
    if mode is None:
        mode = st.config.hierarchical
        # The legacy boolean is an EXPLICIT opt-in (env var or the
        # autotuner's categorical knob): when set it forces the ladder
        # regardless of the tri-state default.
        if st.config.hierarchical_allreduce:
            mode = "on"
    if mode is True:
        mode = "on"
    elif mode is False:
        mode = "off"
    if mode not in HIERARCHICAL_MODES:
        raise InvalidArgumentError(
            f"hierarchical must be one of {HIERARCHICAL_MODES} "
            f"(got {mode!r})")
    if mode == "off":
        return 0
    from horovod_tpu.parallel.mesh import dcn_present, slice_topology

    devices = st.devices or None
    inner = st.config.hierarchical_inner_size
    if mode == "auto":
        # auto = engage only on a REAL multi-slice/DCN mesh, explicit
        # inner size or not — single-slice jobs stay flat (force the
        # ladder there with "on").
        if not dcn_present(devices):
            return 0
        if not inner:
            try:
                _, inner = slice_topology(devices)
            except InvalidArgumentError:
                # Heterogeneous chips-per-domain with no explicit inner:
                # no valid ladder tiling exists — degrade to flat, the
                # reference's is_homogeneous rule.
                return 0
    elif not inner:  # "on" without an explicit inner size
        try:
            domains, per = slice_topology(devices)
            inner = per if domains > 1 else st.local_device_count
        except InvalidArgumentError:
            inner = st.local_device_count
    if inner and 1 < inner < axis_size and axis_size % inner == 0:
        return inner
    return 0


def _pad_up_elems(elems: int, quantum: int) -> int:
    return (elems + quantum - 1) // quantum * quantum


def hier_bucket_layout(elems: int, axis_size: int, inner: int,
                       quantized: bool) -> dict:
    """Static element-count layout of one hierarchical bucket: how the
    flat buffer pads and shards on the ladder. ``m`` is the slice
    (outer/DCN) count; quantized buckets at m > 2 take the two-stage
    exchange, whose all-to-all needs the shard divisible by m as well.
    Shared by the executing path, :func:`ef_residual_specs`,
    :func:`hier_wire_summary` and the HVV105 reconciliation — one
    layout, four consumers, no drift."""
    m = axis_size // inner
    two_stage = quantized and m > 2
    quantum = inner * m if two_stage else inner
    padded = _pad_up_elems(elems, quantum)
    shard = padded // inner
    return {
        "m": m,
        "two_stage": two_stage,
        "padded_elems": padded,
        "shard_elems": shard,
        "sub_elems": shard // m if two_stage else 0,
    }


def _ef_eligible(bucket: "Bucket") -> bool:
    """Buckets the low-bit DCN codec (and so the error-feedback
    residual) applies to: floating dtypes only — integer gradients take
    the plain psum DCN leg."""
    return jnp.issubdtype(jnp.dtype(bucket.dtype), jnp.floating)


def ef_residual_specs(leaves, threshold: int, axis_size: int, inner: int):
    """GLOBAL-shaped ShapeDtypeStructs of the error-feedback residuals
    for a quantized hierarchical exchange over ``leaves`` — one fp32
    vector per quantized stage per floating bucket, in plan order.

    Each residual is rank-LOCAL state: chip ``r`` owns rows
    ``[r*shard : (r+1)*shard)`` of the global vector. Feed these leaves
    through the training step with ``P("hvd")`` partition specs
    (``models.state_partition_specs`` derives them) so shard_map hands
    every chip exactly its own slice; the leaves are created zero by
    ``allreduce_gradients_transform``'s init and updated in place of
    the optimizer state each step. Buckets at 2 slices carry one
    residual (the all-gather exchange quantizes once); buckets at >2
    slices carry two (the two-stage exchange re-quantizes the summed
    sub-shard)."""
    import jax

    specs = []
    for bucket in plan_buckets(leaves, threshold):
        if not _ef_eligible(bucket):
            continue
        itemsize = jnp.dtype(bucket.dtype).itemsize
        layout = hier_bucket_layout(bucket.nbytes // itemsize, axis_size,
                                    inner, quantized=True)
        specs.append(jax.ShapeDtypeStruct(
            (axis_size * layout["shard_elems"],), jnp.float32))
        if layout["two_stage"]:
            specs.append(jax.ShapeDtypeStruct(
                (axis_size * layout["sub_elems"],), jnp.float32))
    return specs


def hier_wire_summary(plan: Sequence[Bucket], axis_size: int, inner: int,
                      compression=Compression.none) -> dict:
    """Per-leg STATIC operand-byte split of a hierarchical bucket plan —
    the ``"wire"`` stamp bench.py records and the numbers
    tools/scaling_model.py prices, derived from the same
    :func:`hier_bucket_layout` the executing path uses (so the stamp is
    checkable against the HVV105-reconciled schedule).

    ``ici_bytes`` = intra-slice reduce-scatter + all-gather operands;
    ``dcn_bytes`` = inter-slice exchange operands (quantized payloads +
    their scale scalars under int8/fp8); ``ratio`` = what the DCN leg
    would have carried at the input dtype over what it carries now
    (1.0 uncompressed, ~4x under int8/fp8 from fp32)."""
    quantizer = compression if is_dcn_wire(compression) else None
    ici = dcn = flat_dcn = 0
    dcn_dtype = None
    for b in plan:
        dt = jnp.dtype(b.dtype)
        elems = b.nbytes // dt.itemsize
        q = quantizer is not None and _ef_eligible(b)
        layout = hier_bucket_layout(elems, axis_size, inner, quantized=q)
        shard = layout["shard_elems"]
        # Quantized buckets dequant-sum in fp32, so the final intra-
        # slice all-gather carries fp32 regardless of the input dtype.
        ag_itemsize = 4 if q else dt.itemsize
        ici += layout["padded_elems"] * dt.itemsize + shard * ag_itemsize
        if q:
            wire = jnp.dtype(quantizer.wire_dtype)
            dcn_dtype = wire.name
            if layout["two_stage"]:
                dcn += (shard + layout["sub_elems"]) * wire.itemsize + 8
            else:
                dcn += shard * wire.itemsize + 4
        else:
            dcn += shard * dt.itemsize
            if dcn_dtype is None:
                dcn_dtype = dt.name
        flat_dcn += shard * dt.itemsize
    return {
        "ici_bytes": int(ici),
        "dcn_bytes": int(dcn),
        "ici_mb": round(ici / (1024 * 1024), 3),
        "dcn_mb": round(dcn / (1024 * 1024), 3),
        "dtype": dcn_dtype,
        "ratio": round(flat_dcn / dcn, 2) if dcn else None,
    }


def _quantized_outer_exchange(shard_v, axis, outer_groups, quantizer,
                              layout, r_in, act):
    """The compressed inter-slice (DCN) leg of one bucket's ladder.

    ``shard_v`` is this chip's intra-slice-reduced 1/inner shard. Two
    shapes (see module docstring): at m == 2 slices, all-gather the
    quantized shards + scales and dequant-sum locally; at m > 2, the
    quantized ring decomposition — all-to-all quantized sub-shards,
    dequant-sum, re-quantize, all-gather — so per-chip DCN wire stays
    ~2(m-1)/m of the QUANTIZED shard instead of growing with m.
    ``r_in`` is the bucket's error-feedback residual tuple (or None for
    feedback-free quantization); returns ``(fp32 summed shard,
    [new residuals])`` with residuals in the pre-divide SUM domain.
    """
    from jax import lax as _lax

    from horovod_tpu.utils import timeline as _tl_names

    new_res = []
    v = shard_v.astype(jnp.float32)
    if r_in is not None:
        v = v + r_in[0]
    q, scale = quantizer.quantize(v)
    if r_in is not None:
        new_res.append(v - quantizer.dequantize(q, scale))
    if not layout["two_stage"]:
        with act(_tl_names.ALLGATHER):
            qs = _lax.all_gather(q, axis, axis=0,
                                 axis_index_groups=outer_groups)
            ss = _lax.all_gather(scale.reshape(1), axis, axis=0,
                                 axis_index_groups=outer_groups)
        out = (qs.astype(jnp.float32) * ss).sum(axis=0)
        return out, new_res
    m = layout["m"]
    with act(_tl_names.ALLTOALL):
        recv = _lax.all_to_all(q.reshape(m, -1), axis, split_axis=0,
                               concat_axis=0,
                               axis_index_groups=outer_groups, tiled=True)
        ss = _lax.all_gather(scale.reshape(1), axis, axis=0,
                             axis_index_groups=outer_groups)
    u = (recv.astype(jnp.float32) * ss).sum(axis=0)
    if r_in is not None:
        u = u + r_in[1]
    q2, scale2 = quantizer.quantize(u)
    if r_in is not None:
        new_res.append(u - quantizer.dequantize(q2, scale2))
    with act(_tl_names.ALLGATHER):
        qg = _lax.all_gather(q2, axis, axis=0,
                             axis_index_groups=outer_groups)
        sg = _lax.all_gather(scale2.reshape(1), axis, axis=0,
                             axis_index_groups=outer_groups)
    out = (qg.astype(jnp.float32) * sg).reshape(-1)
    return out, new_res


def fused_reduce(
    tensors,
    average: bool = True,
    compression=Compression.none,
    op=None,
    fusion_threshold: Optional[int] = None,
    name: Optional[str] = None,
    overlap: Optional[str] = None,
    scatter_threshold: Optional[int] = None,
    hierarchical: Optional[str] = None,
    residuals=None,
):
    """Allreduce a sequence of tensors via fused flat buckets.

    Returns a list of reduced tensors in input order. Works inside an SPMD
    region (psum per bucket) and eagerly (size()==1 identity semantics).
    ``name`` labels the per-tensor collectives on the eager process-level
    path (where names drive the native negotiation and the timeline); the
    SPMD path has no per-tensor identity inside the compiled program.

    ``overlap`` (auto|on|off, default HOROVOD_OVERLAP) selects the
    backward-overlapped emission: reverse bucket order, start-all/
    unpack-later, reduce-scatter+all-gather for buckets >=
    ``scatter_threshold`` bytes (HOROVOD_OVERLAP_SCATTER_THRESHOLD).
    Changes dispatch shape only — results are bit-identical to ``off``.

    ``hierarchical`` (auto|on|off, default HOROVOD_HIERARCHICAL) runs
    each Sum/Average bucket as the two-level intra-slice reduce-scatter
    -> inter-slice exchange -> intra-slice all-gather ladder (module
    docstring); with ``Compression.int8``/``.fp8`` the inter-slice leg
    is absmax-quantized, optionally error-corrected by ``residuals``
    (the per-chip state from :func:`ef_residual_specs` — when passed,
    the return value becomes ``(outputs, new_residuals)``).
    """
    from horovod_tpu.jax import mpi_ops

    if op is None:
        op = mpi_ops.Average if average else mpi_ops.Sum

    st = global_state()
    st.require_init()
    if fusion_threshold is None:
        fusion_threshold = st.config.fusion_threshold
    if scatter_threshold is None:
        scatter_threshold = st.config.overlap_scatter_threshold

    tensors = [jnp.asarray(t) for t in tensors]
    axis = current_spmd_axis()
    if axis is None:
        nproc = st.process_count
        if nproc > 1 and residuals and is_dcn_wire(compression):
            # Same config-drift class as the flat-resolution raise
            # below: EF state exists (init saw an engageable ladder)
            # but the eager lane has no hierarchical path — full-
            # precision bytes would cross the wire while the user
            # believes int8/fp8 EF is active. (Single-process identity
            # passes through: no bytes move at all.)
            raise InvalidArgumentError(
                "error-feedback residuals are present but the multi-"
                "process eager lane has no hierarchical/quantized "
                "exchange — int8/fp8 wire compression requires the "
                "SPMD lane (hvd.spmd_run/spmd_fn); use Compression."
                "fp16/bf16 or none here")
        if nproc == 1:
            out = list(tensors)
        else:
            # Multi-process eager: reduce each via the process-level
            # path (the native core fuses on its own side, so this
            # per-tensor loop is not the per-tensor anti-pattern HVD006
            # flags in user code).
            out = [
                mpi_ops.allreduce(  # hvdlint: disable=HVD006
                    t, average=(op is mpi_ops.Average), op=op,
                    name=f"{name}.{i}" if name else None)
                for i, t in enumerate(tensors)
            ]
        if residuals is not None:  # no DCN leg here: residuals untouched
            return out, tuple(residuals)
        return out

    n = mpi_ops._axis_size(axis)
    # Min/Max/Product fuse just as well as Sum: any elementwise cross-rank
    # reduction distributes over concatenation.
    plain_sum = op is mpi_ops.Average or op is mpi_ops.Sum
    if plain_sum:
        reduce_fn = lax.psum
        # HOROVOD_HIERARCHICAL: run each bucket as the explicit
        # two-level ladder (reference operations.cc:1284-1436) —
        # reduce-scatter in the fast (ICI) domain, exchange 1/inner of
        # the bytes across DCN, all-gather back.
        hier = resolve_hierarchical(hierarchical, n)
    else:
        hier = 0
        try:
            reduce_fn = mpi_ops._REDUCE_FNS[op]
        except KeyError:
            raise InvalidArgumentError(f"Unsupported reduction op: {op}")
    quantizer = compression if (hier and is_dcn_wire(compression)) else None
    if residuals and is_dcn_wire(compression) and quantizer is None:
        # The caller initialized error-feedback state for an engaged
        # ladder (ef_residual_specs at init world size), but on THIS
        # axis the ladder resolves to flat — silently skipping the
        # quantized exchange would let the user believe int8/fp8 EF is
        # active while fp32 flows. Config drift, not a degrade case.
        raise InvalidArgumentError(
            "error-feedback residuals are present but the hierarchical "
            f"ladder resolves to FLAT on this {n}-way axis "
            "(HOROVOD_HIERARCHICAL_INNER_SIZE must satisfy 1 < inner "
            f"< {n} and divide it): the optimizer state was initialized "
            "against a different world/axis size — re-init the "
            "optimizer (fusion.ef_residual_specs) for this axis")
    compressed = []
    ctxs = []
    for t in tensors:
        c, ctx = compression.compress(t)
        compressed.append(c)
        ctxs.append(ctx)

    plan = plan_buckets(compressed, fusion_threshold)
    use_overlap = resolve_overlap(overlap, len(plan))
    # The rs+ag form needs the plain flat psum semantics (Min/Max/
    # Product have no scatter primitive) and >1 rank for the scatter to
    # mean anything; hierarchical buckets never take it — the ladder
    # already decomposes into schedulable halves.
    can_scatter = use_overlap and plain_sum and not hier and n > 1

    # Error-feedback residual slots: plan index -> (offset, count) into
    # the ``residuals`` tuple, in plan order (the structure
    # ef_residual_specs promises). Updated residuals land in
    # ``new_residuals`` at the same offsets.
    ef_map = {}
    if hier and quantizer is not None:
        off = 0
        for pi, b in enumerate(plan):
            if not _ef_eligible(b):
                continue
            layout = hier_bucket_layout(
                b.nbytes // jnp.dtype(b.dtype).itemsize, n, hier,
                quantized=True)
            count = 2 if layout["two_stage"] else 1
            ef_map[pi] = (off, count)
            off += count
        if residuals is not None and len(residuals) != off:
            raise InvalidArgumentError(
                f"error-feedback residuals carry {len(residuals)} "
                f"leaves but this plan needs {off} (one per quantized "
                "stage per floating bucket, plan order — rebuild them "
                "with fusion.ef_residual_specs after changing the "
                "fusion threshold, world size or inner size)")
    new_residuals = list(residuals) if residuals is not None else None

    # Per-bucket observability (the SPMD half of the reference's
    # per-tensor activity taxonomy, operations.h:29-50): each bucket's
    # collective is built under a jax.named_scope — the name lands in
    # the HLO metadata, so device profiles (jax.profiler /
    # tools/profile_step.py) attribute its time by name — and, when
    # HOROVOD_TIMELINE is active, emits MEMCPY_IN_FUSION_BUFFER /
    # ALLREDUCE (or REDUCESCATTER+ALLGATHER on the scatter form) /
    # MEMCPY_OUT_FUSION_BUFFER spans on a per-bucket track at TRACE time
    # (this code runs once per compile; the spans record the bucket PLAN
    # — members/bytes/dtype/issue order — not per-step device time,
    # which is stated in the span args; per-step device time is the
    # profiler's job, per-step host dispatch is XLA_EXECUTE's). Under
    # overlap the B span opens at ISSUE and closes at UNPACK, so the
    # trace shows every in-flight bucket between its collective start
    # and its fusion-buffer unpack.
    import contextlib

    import jax as _jax

    from horovod_tpu.utils import timeline as _tl_names
    from horovod_tpu.utils.timeline import activity as _activity

    tl = getattr(st, "timeline", None)
    emit = tl is not None and tl.enabled

    def _act(track, act_name):
        return (_activity(tl, track, act_name) if emit
                else contextlib.nullcontext())

    results: List = [None] * len(tensors)
    # Members whose averaging division already happened on the scattered
    # shard (the "sharded update": 1/n of the elementwise work, before
    # the all-gather) — the tail must not divide them again.
    averaged = [False] * len(tensors)

    def _pack_flat(members, bucket_name):
        """Memcpy-in: ravel+concatenate the bucket members into the flat
        fusion buffer (shared by the hierarchical and scatter forms)."""
        with _act(bucket_name, _tl_names.MEMCPY_IN_FUSION_BUFFER):
            return (jnp.concatenate(
                [compressed[i].ravel() for i in members])
                if len(members) > 1
                else compressed[members[0]].ravel())

    def _issue(k, pi, bucket: Bucket):
        """Emit bucket ``bucket``'s collective (k-th in issue order,
        ``pi``-th in the plan); return the unpack closure that splits
        results back out."""
        dtype = jnp.dtype(bucket.dtype)
        bucket_name = f"{name or 'fused'}.{dtype.name}.b{bucket.index}"
        scope = f"hvd_allreduce_{bucket_name}".replace(".", "_")
        members = list(bucket.members)
        scatter = can_scatter and bucket.nbytes >= scatter_threshold
        hier_q = hier and quantizer is not None and _ef_eligible(bucket)
        if hier:
            path = (f"hier_{jnp.dtype(quantizer.wire_dtype).name}"
                    if hier_q else "hier")
        else:
            path = "rs_ag" if scatter else "psum"
        if emit:
            tl.start(bucket_name, _tl_names.ALLREDUCE,
                     args={"span": "trace", "tensors": len(members),
                           "bytes": int(bucket.nbytes),
                           "overlap": bool(use_overlap), "issue": k,
                           # Sequential emission unpacks each bucket
                           # before issuing the next: never >1 in flight.
                           "in_flight": k + 1 if use_overlap else 1,
                           "path": path,
                           **({"inner": int(hier)} if hier else {})})
        # The hierarchical ladder and the scatter form both hand the
        # unpack a FLAT reduced buffer; the psum forms keep shape.
        flat_form = bool(scatter or hier)
        try:
            with _jax.named_scope(scope):
                if hier:
                    flat = _pack_flat(members, bucket_name)
                    size = flat.size
                    layout = hier_bucket_layout(size, n, hier,
                                                quantized=hier_q)
                    pad = layout["padded_elems"] - size
                    if pad:
                        flat = jnp.pad(flat, (0, pad))
                    # Average: divide the dequantized/summed 1/inner
                    # shard BEFORE the gather (commutes elementwise —
                    # bit-identical to a tail divide, 1/inner the work);
                    # cast compressors keep the historical tail divide
                    # so hier-off/on share one division sequence.
                    div_on_shard = op is mpi_ops.Average and (
                        hier_q or compression is Compression.none)
                    r_in = None
                    if hier_q and residuals is not None:
                        offr, cnt = ef_map[pi]
                        r_in = tuple(residuals[offr:offr + cnt])
                        want = (layout["shard_elems"],)
                        if tuple(r_in[0].shape) != want:
                            raise InvalidArgumentError(
                                f"error-feedback residual for bucket "
                                f"{bucket_name} arrives with shape "
                                f"{tuple(r_in[0].shape)}, expected the "
                                f"per-chip shard {want}: residual "
                                "leaves are rank-local state and must "
                                "enter the step sharded P(axis) — pass "
                                "the train state through models."
                                "state_partition_specs")

                    def _outer(shard_v, ax, og, _layout=layout,
                               _r=r_in, _div=div_on_shard, _pi=pi,
                               _bn=bucket_name, _hq=hier_q):
                        if _hq:
                            out_s, res_new = _quantized_outer_exchange(
                                shard_v, ax, og, quantizer, _layout, _r,
                                lambda a: _act(_bn, a))
                            if _r is not None:
                                offr, cnt = ef_map[_pi]
                                new_residuals[offr:offr + cnt] = res_new
                        else:
                            out_s = lax.psum(shard_v, ax,
                                             axis_index_groups=og)
                        if _div:
                            out_s = out_s / n
                        return out_s

                    from horovod_tpu.parallel.mesh import (
                        hierarchical_ladder_in_axis,
                    )

                    with _act(bucket_name, _tl_names.REDUCESCATTER):
                        reduced = hierarchical_ladder_in_axis(
                            flat, axis, hier, outer_exchange=_outer)
                    if div_on_shard:
                        for i in members:
                            averaged[i] = True
                    if pad:
                        reduced = reduced[:size]
                elif scatter:
                    flat = _pack_flat(members, bucket_name)
                    size = flat.size
                    pad = (-size) % n
                    if pad:
                        flat = jnp.pad(flat, (0, pad))
                    with _act(bucket_name, _tl_names.REDUCESCATTER):
                        shard = lax.psum_scatter(
                            flat, axis, scatter_dimension=0, tiled=True)
                    if op is mpi_ops.Average and compression is Compression.none:
                        # Sharded update: divide the 1/n shard, not the
                        # gathered whole — elementwise division commutes
                        # with the gather, so this is bit-identical to
                        # dividing after (and 1/n of the work). Under
                        # wire compression the division stays in the
                        # decompressed dtype at the tail instead.
                        shard = shard / n
                        for i in members:
                            averaged[i] = True
                    with _act(bucket_name, _tl_names.ALLGATHER):
                        reduced = lax.all_gather(shard, axis, tiled=True)
                    if pad:
                        reduced = reduced[:size]
                elif len(members) == 1:
                    reduced = reduce_fn(compressed[members[0]], axis)
                else:
                    reduced = reduce_fn(_pack_flat(members, bucket_name),
                                        axis)
        except Exception:
            if emit:
                tl.end(bucket_name, _tl_names.ALLREDUCE)
            raise

        def _unpack():
            try:
                with _jax.named_scope(scope):
                    if len(members) == 1 and not flat_form:
                        results[members[0]] = reduced
                        return
                    with _act(bucket_name,
                              _tl_names.MEMCPY_OUT_FUSION_BUFFER):
                        offset = 0
                        for i in members:
                            sz = compressed[i].size
                            results[i] = reduced[offset:offset + sz].reshape(
                                compressed[i].shape)
                            offset += sz
            finally:
                if emit:
                    tl.end(bucket_name, _tl_names.ALLREDUCE)

        return _unpack

    if use_overlap:
        # Reverse bucket order = backward availability order (autodiff
        # produces the LAST layers' gradients first): start every
        # collective as its bucket's gradients become available, unpack
        # afterwards in forward order — the start-all/done-later shape
        # XLA's async collective scheduler hides under the remaining
        # backward compute.
        unpacks = [None] * len(plan)
        for k, bi in enumerate(reversed(range(len(plan)))):
            unpacks[bi] = _issue(k, bi, plan[bi])
        for unpack in unpacks:
            unpack()
    else:
        for k, bucket in enumerate(plan):
            _issue(k, k, bucket)()

    out = []
    for i, t in enumerate(tensors):
        r = compression.decompress(results[i], ctxs[i])
        if op is mpi_ops.Average and not averaged[i]:
            r = r / n
        out.append(r.astype(t.dtype) if r.dtype != t.dtype else r)
    if residuals is not None:
        return out, tuple(new_residuals)
    return out
