"""Tensor fusion: bucketed flat-buffer collectives, overlap-scheduled.

TPU-native rebuild of the reference's fusion machinery — the 64 MB fusion
buffer (horovod/common/fusion_buffer_manager.h:50-55), the response-merging
look-ahead that packs same-dtype tensors into one collective
(operations.cc:2160-2264), and the MEMCPY_IN/OUT_FUSION_BUFFER data plane
(operations.cc:1491-1586).

Mapping onto XLA:

* the persistent device-side fusion buffer becomes a traced flat
  concatenation — XLA allocates and reuses it across steps;
* "memcpy into the fusion buffer" becomes ``ravel``+``concatenate`` which
  XLA fuses into the collective's prologue;
* one ``lax.psum`` per bucket amortizes ICI latency over many small
  gradients the same way one NCCL launch amortized ring latency;
* bucket boundaries respect HOROVOD_FUSION_THRESHOLD so the env knob (and
  the autotuner that drives it) keeps its meaning.

Overlap scheduling (HOROVOD_OVERLAP=auto|on|off): the reference hid the
gradient exchange behind backward compute by firing an allreduce from each
gradient hook as autograd produced it (Sergeev & Del Balso 2018; PyTorch
DDP's reverse-order buckets, Li et al. VLDB 2020). Under XLA the step is
one program, so the same win is a *scheduling shape* problem: with overlap
on, per-bucket collectives are issued in REVERSE bucket order — the order
backward produces gradients, last layers first — as a start-all/
unpack-later sequence, so each bucket's collective depends only on its own
members and XLA's async collective (start/done) scheduler can slide it
under the remaining backward compute instead of serializing one
post-backward block. Buckets at or above HOROVOD_OVERLAP_SCATTER_THRESHOLD
additionally take the ``psum_scatter`` -> sharded-update -> ``all_gather``
form: identical wire bytes (reduce-scatter + all-gather IS how a ring
allreduce decomposes) and identical numerics, but two independently
schedulable halves — ZeRO-shaped communication with plain-DP semantics
(optimizer state stays replicated; contrast :mod:`horovod_tpu.jax.zero`).
Overlap NEVER changes results: the emission order and collective shape
change, the math does not (pinned bit-exactly in tests/test_overlap.py).

Same-dtype-only fusion matches the reference (it fused only responses with
identical dtype/device signatures, operations.cc:2175-2230).
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.config import OVERLAP_MODES
from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.jax.compression import Compression


def _plan_buckets(sizes_bytes: Sequence[int], threshold: int) -> List[List[int]]:
    """Greedy contiguous bucketing: consecutive tensors pack into a bucket
    until adding the next would exceed ``threshold`` (an oversize tensor
    gets its own bucket, like an oversize response in the reference)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(sizes_bytes):
        if cur and cur_bytes + nb > threshold:
            buckets.append(cur)
            cur = []
            cur_bytes = 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


class Bucket(NamedTuple):
    """One fused-collective bucket of the plan (public accounting record —
    tools/scaling_model.py and the bucket-byte tests consume these)."""

    dtype: str        # wire dtype name, e.g. "float32"
    index: int        # position within this dtype's bucket sequence
    members: tuple    # indices into the input tensor list, input order
    nbytes: int       # payload bytes (sum of member bytes, unpadded)
    oversize: bool    # single tensor alone exceeding the fusion threshold


def _leaf_size(leaf) -> int:
    size = getattr(leaf, "size", None)
    if size is None:  # ShapeDtypeStruct on older jax: derive from shape
        size = int(math.prod(leaf.shape))
    return int(size)


def plan_buckets(leaves, threshold: int) -> List[Bucket]:
    """The full bucket plan for ``leaves`` (arrays or ShapeDtypeStructs):
    grouped by dtype (first-appearance order), greedily packed to
    ``threshold`` bytes within each group, forward (input) order.

    This is exactly the plan :func:`fused_reduce` executes, exposed so the
    scaling model and tests can account bucket bytes without tracing."""
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    plan: List[Bucket] = []
    for dtype, idxs in by_dtype.items():
        sizes = [_leaf_size(leaves[i]) * dtype.itemsize for i in idxs]
        for b, bucket in enumerate(_plan_buckets(sizes, threshold)):
            nbytes = sum(sizes[j] for j in bucket)
            plan.append(Bucket(
                dtype=dtype.name,
                index=b,
                members=tuple(idxs[j] for j in bucket),
                nbytes=nbytes,
                oversize=len(bucket) == 1 and nbytes > threshold,
            ))
    return plan


def plan_summary(plan: Sequence[Bucket]) -> dict:
    """Compact accounting of a bucket plan: the numbers the scaling model
    consumes and bench JSON stamps alongside the overlap knob."""
    total = sum(b.nbytes for b in plan)
    return {
        "count": len(plan),
        "total_bytes": total,
        "total_mb": round(total / (1024 * 1024), 2),
        "oversize_singletons": sum(1 for b in plan if b.oversize),
        "largest_bytes": max((b.nbytes for b in plan), default=0),
    }


def resolve_overlap(mode: Optional[str], n_buckets: int) -> bool:
    """Resolve the overlap knob to a concrete decision for one plan.

    ``auto`` engages overlap emission whenever the plan has >= 2 buckets
    (with a single bucket there is nothing to interleave — the legacy
    single-pass emission is kept so historical wire shapes stay
    byte-identical); ``on`` forces the overlap shape even for one bucket;
    ``off`` is the legacy post-backward block. ``None`` reads the
    HOROVOD_OVERLAP config default.
    """
    if mode is None:
        mode = global_state().config.overlap
    if mode is True:
        mode = "on"
    elif mode is False:
        mode = "off"
    if mode not in OVERLAP_MODES:
        raise InvalidArgumentError(
            f"overlap must be one of {OVERLAP_MODES} (got {mode!r})")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return n_buckets >= 2


def _hierarchical_inner(st, axis_size: int, enabled: bool) -> int:
    """Fast-domain size for the two-level ladder, or 0 when the flat
    collective should be used. Auto mode uses chips-per-process (the
    reference's local/cross comm split, operations.cc:1760-1797)."""
    if not enabled:
        return 0
    inner = st.config.hierarchical_inner_size or st.local_device_count
    if 1 < inner < axis_size and axis_size % inner == 0:
        return inner
    return 0


def fused_reduce(
    tensors,
    average: bool = True,
    compression=Compression.none,
    op=None,
    fusion_threshold: Optional[int] = None,
    name: Optional[str] = None,
    overlap: Optional[str] = None,
    scatter_threshold: Optional[int] = None,
):
    """Allreduce a sequence of tensors via fused flat buckets.

    Returns a list of reduced tensors in input order. Works inside an SPMD
    region (psum per bucket) and eagerly (size()==1 identity semantics).
    ``name`` labels the per-tensor collectives on the eager process-level
    path (where names drive the native negotiation and the timeline); the
    SPMD path has no per-tensor identity inside the compiled program.

    ``overlap`` (auto|on|off, default HOROVOD_OVERLAP) selects the
    backward-overlapped emission: reverse bucket order, start-all/
    unpack-later, reduce-scatter+all-gather for buckets >=
    ``scatter_threshold`` bytes (HOROVOD_OVERLAP_SCATTER_THRESHOLD).
    Changes dispatch shape only — results are bit-identical to ``off``.
    """
    from horovod_tpu.jax import mpi_ops

    if op is None:
        op = mpi_ops.Average if average else mpi_ops.Sum

    st = global_state()
    st.require_init()
    if fusion_threshold is None:
        fusion_threshold = st.config.fusion_threshold
    if scatter_threshold is None:
        scatter_threshold = st.config.overlap_scatter_threshold

    tensors = [jnp.asarray(t) for t in tensors]
    axis = current_spmd_axis()
    if axis is None:
        nproc = st.process_count
        if nproc == 1:
            return list(tensors)
        # Multi-process eager: reduce each via the process-level path (the
        # native core fuses on its own side, so this per-tensor loop is
        # not the per-tensor anti-pattern HVD006 flags in user code).
        return [
            mpi_ops.allreduce(  # hvdlint: disable=HVD006
                t, average=(op is mpi_ops.Average), op=op,
                name=f"{name}.{i}" if name else None)
            for i, t in enumerate(tensors)
        ]

    n = mpi_ops._axis_size(axis)
    # Min/Max/Product fuse just as well as Sum: any elementwise cross-rank
    # reduction distributes over concatenation.
    plain_sum = op is mpi_ops.Average or op is mpi_ops.Sum
    if plain_sum:
        reduce_fn = lax.psum
        # HOROVOD_HIERARCHICAL_ALLREDUCE: route sum-reductions through the
        # explicit two-level ladder (reference operations.cc:1284-1436) —
        # reduce-scatter in the fast (ICI) domain, cross-reduce 1/inner of
        # the bytes, all-gather back.
        inner = _hierarchical_inner(st, n, st.config.hierarchical_allreduce)
        if inner:
            from horovod_tpu.parallel.mesh import hierarchical_allreduce_in_axis

            def reduce_fn(v, ax, _inner=inner):
                return hierarchical_allreduce_in_axis(v, ax, _inner)
    else:
        inner = 0
        try:
            reduce_fn = mpi_ops._REDUCE_FNS[op]
        except KeyError:
            raise InvalidArgumentError(f"Unsupported reduction op: {op}")
    compressed = []
    ctxs = []
    for t in tensors:
        c, ctx = compression.compress(t)
        compressed.append(c)
        ctxs.append(ctx)

    plan = plan_buckets(compressed, fusion_threshold)
    use_overlap = resolve_overlap(overlap, len(plan))
    # The rs+ag form needs the plain flat psum semantics (the ladder
    # already decomposes; Min/Max/Product have no scatter primitive) and
    # >1 rank for the scatter to mean anything.
    can_scatter = use_overlap and plain_sum and not inner and n > 1

    # Per-bucket observability (the SPMD half of the reference's
    # per-tensor activity taxonomy, operations.h:29-50): each bucket's
    # collective is built under a jax.named_scope — the name lands in
    # the HLO metadata, so device profiles (jax.profiler /
    # tools/profile_step.py) attribute its time by name — and, when
    # HOROVOD_TIMELINE is active, emits MEMCPY_IN_FUSION_BUFFER /
    # ALLREDUCE (or REDUCESCATTER+ALLGATHER on the scatter form) /
    # MEMCPY_OUT_FUSION_BUFFER spans on a per-bucket track at TRACE time
    # (this code runs once per compile; the spans record the bucket PLAN
    # — members/bytes/dtype/issue order — not per-step device time,
    # which is stated in the span args; per-step device time is the
    # profiler's job, per-step host dispatch is XLA_EXECUTE's). Under
    # overlap the B span opens at ISSUE and closes at UNPACK, so the
    # trace shows every in-flight bucket between its collective start
    # and its fusion-buffer unpack.
    import contextlib

    import jax as _jax

    from horovod_tpu.utils import timeline as _tl_names
    from horovod_tpu.utils.timeline import activity as _activity

    tl = getattr(st, "timeline", None)
    emit = tl is not None and tl.enabled

    def _act(track, act_name):
        return (_activity(tl, track, act_name) if emit
                else contextlib.nullcontext())

    results: List = [None] * len(tensors)
    # Members whose averaging division already happened on the scattered
    # shard (the "sharded update": 1/n of the elementwise work, before
    # the all-gather) — the tail must not divide them again.
    averaged = [False] * len(tensors)

    def _issue(k, bucket: Bucket):
        """Emit bucket ``bucket``'s collective (k-th in issue order);
        return the unpack closure that splits results back out."""
        dtype = jnp.dtype(bucket.dtype)
        bucket_name = f"{name or 'fused'}.{dtype.name}.b{bucket.index}"
        scope = f"hvd_allreduce_{bucket_name}".replace(".", "_")
        members = list(bucket.members)
        scatter = can_scatter and bucket.nbytes >= scatter_threshold
        if emit:
            tl.start(bucket_name, _tl_names.ALLREDUCE,
                     args={"span": "trace", "tensors": len(members),
                           "bytes": int(bucket.nbytes),
                           "overlap": bool(use_overlap), "issue": k,
                           # Sequential emission unpacks each bucket
                           # before issuing the next: never >1 in flight.
                           "in_flight": k + 1 if use_overlap else 1,
                           "path": "rs_ag" if scatter else "psum"})
        try:
            with _jax.named_scope(scope):
                if scatter:
                    with _act(bucket_name, _tl_names.MEMCPY_IN_FUSION_BUFFER):
                        flat = (jnp.concatenate(
                            [compressed[i].ravel() for i in members])
                            if len(members) > 1
                            else compressed[members[0]].ravel())
                    size = flat.size
                    pad = (-size) % n
                    if pad:
                        flat = jnp.pad(flat, (0, pad))
                    with _act(bucket_name, _tl_names.REDUCESCATTER):
                        shard = lax.psum_scatter(
                            flat, axis, scatter_dimension=0, tiled=True)
                    if op is mpi_ops.Average and compression is Compression.none:
                        # Sharded update: divide the 1/n shard, not the
                        # gathered whole — elementwise division commutes
                        # with the gather, so this is bit-identical to
                        # dividing after (and 1/n of the work). Under
                        # wire compression the division stays in the
                        # decompressed dtype at the tail instead.
                        shard = shard / n
                        for i in members:
                            averaged[i] = True
                    with _act(bucket_name, _tl_names.ALLGATHER):
                        reduced = lax.all_gather(shard, axis, tiled=True)
                    if pad:
                        reduced = reduced[:size]
                elif len(members) == 1:
                    reduced = reduce_fn(compressed[members[0]], axis)
                else:
                    with _act(bucket_name, _tl_names.MEMCPY_IN_FUSION_BUFFER):
                        flat = jnp.concatenate(
                            [compressed[i].ravel() for i in members])
                    reduced = reduce_fn(flat, axis)
        except Exception:
            if emit:
                tl.end(bucket_name, _tl_names.ALLREDUCE)
            raise

        def _unpack():
            try:
                with _jax.named_scope(scope):
                    if len(members) == 1 and not scatter:
                        results[members[0]] = reduced
                        return
                    with _act(bucket_name,
                              _tl_names.MEMCPY_OUT_FUSION_BUFFER):
                        offset = 0
                        for i in members:
                            sz = compressed[i].size
                            results[i] = reduced[offset:offset + sz].reshape(
                                compressed[i].shape)
                            offset += sz
            finally:
                if emit:
                    tl.end(bucket_name, _tl_names.ALLREDUCE)

        return _unpack

    if use_overlap:
        # Reverse bucket order = backward availability order (autodiff
        # produces the LAST layers' gradients first): start every
        # collective as its bucket's gradients become available, unpack
        # afterwards in forward order — the start-all/done-later shape
        # XLA's async collective scheduler hides under the remaining
        # backward compute.
        unpacks = [None] * len(plan)
        for k, bi in enumerate(reversed(range(len(plan)))):
            unpacks[bi] = _issue(k, plan[bi])
        for unpack in unpacks:
            unpack()
    else:
        for k, bucket in enumerate(plan):
            _issue(k, bucket)()

    out = []
    for i, t in enumerate(tensors):
        r = compression.decompress(results[i], ctxs[i])
        if op is mpi_ops.Average and not averaged[i]:
            r = r / n
        out.append(r.astype(t.dtype) if r.dtype != t.dtype else r)
    return out
