"""Tensor fusion: bucketed flat-buffer collectives.

TPU-native rebuild of the reference's fusion machinery — the 64 MB fusion
buffer (horovod/common/fusion_buffer_manager.h:50-55), the response-merging
look-ahead that packs same-dtype tensors into one collective
(operations.cc:2160-2264), and the MEMCPY_IN/OUT_FUSION_BUFFER data plane
(operations.cc:1491-1586).

Mapping onto XLA:

* the persistent device-side fusion buffer becomes a traced flat
  concatenation — XLA allocates and reuses it across steps;
* "memcpy into the fusion buffer" becomes ``ravel``+``concatenate`` which
  XLA fuses into the collective's prologue;
* one ``lax.psum`` per bucket amortizes ICI latency over many small
  gradients the same way one NCCL launch amortized ring latency;
* bucket boundaries respect HOROVOD_FUSION_THRESHOLD so the env knob (and
  the autotuner that drives it) keeps its meaning.

Same-dtype-only fusion matches the reference (it fused only responses with
identical dtype/device signatures, operations.cc:2175-2230).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.jax.compression import Compression


def _plan_buckets(sizes_bytes: Sequence[int], threshold: int) -> List[List[int]]:
    """Greedy contiguous bucketing: consecutive tensors pack into a bucket
    until adding the next would exceed ``threshold`` (an oversize tensor
    gets its own bucket, like an oversize response in the reference)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(sizes_bytes):
        if cur and cur_bytes + nb > threshold:
            buckets.append(cur)
            cur = []
            cur_bytes = 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def _hierarchical_inner(st, axis_size: int, enabled: bool) -> int:
    """Fast-domain size for the two-level ladder, or 0 when the flat
    collective should be used. Auto mode uses chips-per-process (the
    reference's local/cross comm split, operations.cc:1760-1797)."""
    if not enabled:
        return 0
    inner = st.config.hierarchical_inner_size or st.local_device_count
    if 1 < inner < axis_size and axis_size % inner == 0:
        return inner
    return 0


def fused_reduce(
    tensors,
    average: bool = True,
    compression=Compression.none,
    op=None,
    fusion_threshold: Optional[int] = None,
    name: Optional[str] = None,
):
    """Allreduce a sequence of tensors via fused flat buckets.

    Returns a list of reduced tensors in input order. Works inside an SPMD
    region (psum per bucket) and eagerly (size()==1 identity semantics).
    ``name`` labels the per-tensor collectives on the eager process-level
    path (where names drive the native negotiation and the timeline); the
    SPMD path has no per-tensor identity inside the compiled program.
    """
    from horovod_tpu.jax import mpi_ops

    if op is None:
        op = mpi_ops.Average if average else mpi_ops.Sum

    st = global_state()
    st.require_init()
    if fusion_threshold is None:
        fusion_threshold = st.config.fusion_threshold

    tensors = [jnp.asarray(t) for t in tensors]
    axis = current_spmd_axis()
    if axis is None:
        nproc = st.process_count
        if nproc == 1:
            return list(tensors)
        # Multi-process eager: reduce each via the process-level path (the
        # native core fuses on its own side).
        return [
            mpi_ops.allreduce(
                t, average=(op is mpi_ops.Average), op=op,
                name=f"{name}.{i}" if name else None)
            for i, t in enumerate(tensors)
        ]

    n = mpi_ops._axis_size(axis)
    # Min/Max/Product fuse just as well as Sum: any elementwise cross-rank
    # reduction distributes over concatenation.
    if op is mpi_ops.Average or op is mpi_ops.Sum:
        reduce_fn = lax.psum
        # HOROVOD_HIERARCHICAL_ALLREDUCE: route sum-reductions through the
        # explicit two-level ladder (reference operations.cc:1284-1436) —
        # reduce-scatter in the fast (ICI) domain, cross-reduce 1/inner of
        # the bytes, all-gather back.
        inner = _hierarchical_inner(st, n, st.config.hierarchical_allreduce)
        if inner:
            from horovod_tpu.parallel.mesh import hierarchical_allreduce_in_axis

            def reduce_fn(v, ax, _inner=inner):
                return hierarchical_allreduce_in_axis(v, ax, _inner)
    else:
        try:
            reduce_fn = mpi_ops._REDUCE_FNS[op]
        except KeyError:
            raise InvalidArgumentError(f"Unsupported reduction op: {op}")
    compressed = []
    ctxs = []
    for t in tensors:
        c, ctx = compression.compress(t)
        compressed.append(c)
        ctxs.append(ctx)

    # Group indices by wire dtype, preserving order within a group.
    by_dtype: dict = {}
    for i, c in enumerate(compressed):
        by_dtype.setdefault(jnp.dtype(c.dtype), []).append(i)

    # Per-bucket observability (the SPMD half of the reference's
    # per-tensor activity taxonomy, operations.h:29-50): each bucket's
    # collective is built under a jax.named_scope — the name lands in
    # the HLO metadata, so device profiles (jax.profiler /
    # tools/profile_step.py) attribute its time by name — and, when
    # HOROVOD_TIMELINE is active, emits MEMCPY_IN_FUSION_BUFFER /
    # ALLREDUCE / MEMCPY_OUT_FUSION_BUFFER spans on a per-bucket track
    # at TRACE time (this code runs once per compile; the spans record
    # the bucket PLAN — members/bytes/dtype — not per-step device time,
    # which is stated in the span args; per-step device time is the
    # profiler's job, per-step host dispatch is XLA_EXECUTE's).
    import contextlib

    import jax as _jax

    from horovod_tpu.utils import timeline as _tl_names
    from horovod_tpu.utils.timeline import activity as _activity

    tl = getattr(st, "timeline", None)
    emit = tl is not None and tl.enabled

    @contextlib.contextmanager
    def _span(track, act, args=None):
        """B/E-paired top-level span (activity() covers the nested
        MEMCPY spans; this pairs start/end the same exception-safe
        way). No-ops when the timeline is off."""
        if not emit:
            yield
            return
        tl.start(track, act, args=args)
        try:
            yield
        finally:
            tl.end(track, act)

    def _act(track, act_name):
        return (_activity(tl, track, act_name) if emit
                else contextlib.nullcontext())

    results: List = [None] * len(tensors)
    for dtype, idxs in by_dtype.items():
        sizes = [compressed[i].size * dtype.itemsize for i in idxs]
        for b, bucket in enumerate(_plan_buckets(sizes, fusion_threshold)):
            members = [idxs[j] for j in bucket]
            nbytes = sum(sizes[j] for j in bucket)
            bucket_name = f"{name or 'fused'}.{dtype.name}.b{b}"
            scope = f"hvd_allreduce_{bucket_name}".replace(".", "_")
            with _span(bucket_name, _tl_names.ALLREDUCE,
                       args={"span": "trace", "tensors": len(members),
                             "bytes": int(nbytes)}), \
                 _jax.named_scope(scope):
                if len(members) == 1:
                    i = members[0]
                    results[i] = reduce_fn(compressed[i], axis)
                    continue
                with _act(bucket_name, _tl_names.MEMCPY_IN_FUSION_BUFFER):
                    flat = jnp.concatenate(
                        [compressed[i].ravel() for i in members]
                    )
                reduced = reduce_fn(flat, axis)
                with _act(bucket_name, _tl_names.MEMCPY_OUT_FUSION_BUFFER):
                    offset = 0
                    for i in members:
                        sz = compressed[i].size
                        results[i] = reduced[offset : offset + sz].reshape(
                            compressed[i].shape
                        )
                        offset += sz

    out = []
    for i, t in enumerate(tensors):
        r = compression.decompress(results[i], ctxs[i])
        if op is mpi_ops.Average:
            r = r / n
        out.append(r.astype(t.dtype) if r.dtype != t.dtype else r)
    return out
