"""Process-level eager collectives.

The reference's eager path moved concrete tensors between OS processes over
MPI/NCCL from a background thread (operations.cc:1491-1612). The TPU-native
equivalent moves concrete host arrays between *processes* over the JAX
distributed runtime (ICI within a slice, DCN across slices) — there is no
background thread because JAX dispatch is already asynchronous.

Only used when ``jax.process_count() > 1`` (multi-host); single-process jobs
short-circuit in mpi_ops.py to the reference's size()==1 semantics, and
pure-CPU multi-process jobs use the native core (horovod_tpu.torch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def process_allreduce(x):
    """Elementwise sum of each process's array."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    return jnp.sum(gathered, axis=0)


def process_allgather(x):
    """Concatenate each process's array along dim 0 (ragged allowed when
    trailing dims agree, matching reference allgatherv semantics
    operations.cc:843-925)."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    # process_allgather stacks along a new leading axis when shapes agree.
    return jnp.concatenate(list(gathered), axis=0) if gathered.ndim > jnp.asarray(x).ndim else gathered


def process_broadcast(x, root_rank: int):
    """Every process receives process ``root_rank``'s value.

    A true one-to-all broadcast for any root (``is_source`` selects the
    root), matching MPI_Bcast's O(bytes) per-link cost (reference
    operations.cc:1592-1612). Round-1 version allgathered for non-zero
    roots — O(size x bytes) on DCN — which is the wrong shape at pod scale.
    """
    from jax.experimental import multihost_utils

    x = jnp.asarray(x)
    return multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root_rank
    )
