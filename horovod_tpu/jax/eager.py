"""Process-level eager collectives.

The reference's eager path moved concrete tensors between OS processes over
MPI/NCCL from a background thread (operations.cc:1491-1612). The TPU-native
equivalent moves concrete host arrays between *processes* over the JAX
distributed runtime (ICI within a slice, DCN across slices) — there is no
background thread because JAX dispatch is already asynchronous.

Only used when ``jax.process_count() > 1`` (multi-host); single-process jobs
short-circuit in mpi_ops.py to the reference's size()==1 semantics, and
pure-CPU multi-process jobs use the native core (horovod_tpu.torch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def process_allreduce(x):
    """Elementwise sum of each process's array."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    return jnp.sum(gathered, axis=0)


def process_allgather(x):
    """Concatenate each process's array along dim 0 (ragged allowed when
    trailing dims agree, matching reference allgatherv semantics
    operations.cc:843-925)."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    # process_allgather stacks along a new leading axis when shapes agree.
    return jnp.concatenate(list(gathered), axis=0) if gathered.ndim > jnp.asarray(x).ndim else gathered


def process_broadcast(x, root_rank: int):
    """Every process receives process ``root_rank``'s value.

    A true one-to-all broadcast for any root (``is_source`` selects the
    root), matching MPI_Bcast's O(bytes) per-link cost (reference
    operations.cc:1592-1612). Round-1 version allgathered for non-zero
    roots — O(size x bytes) on DCN — which is the wrong shape at pod scale.
    """
    from jax.experimental import multihost_utils

    x = jnp.asarray(x)
    return multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root_rank
    )


# --------------------------------------------------------------------------
# Scalable exchange shapes: alltoall / reducescatter compiled over a
# one-representative-device-per-process mesh. The old eager fallbacks
# (allgather-then-select, full-reduce-then-slice) moved O(size x bytes)
# per rank; these compile the REAL primitive — lax.all_to_all's pairwise
# exchange, lax.psum_scatter's ring — over the process world, so the wire
# cost has the MPI shape (O(bytes) / (n-1)/n bytes per rank) while the
# data plane rides the same distributed runtime as the other eager ops.


def _process_mesh():
    """1-D mesh with ONE representative device per process, process order."""
    import numpy as np
    from jax.sharding import Mesh

    reps = {}
    for d in jax.devices():
        reps.setdefault(d.process_index, d)
    devs = np.array([reps[i] for i in range(jax.process_count())])
    return Mesh(devs, ("proc",))


def _alltoall_on_axis(t, axis, split_axis: int, concat_axis: int):
    """Per-rank alltoall body: scatter dim ``split_axis`` splits, gather
    received splits along ``concat_axis`` (the pairwise-exchange data
    plane; equivalence vs the old allgather-then-select shape is pinned
    in tests/test_collectives.py)."""
    from jax import lax

    return lax.all_to_all(t, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def _reducescatter_on_axis(t, axis):
    """Per-rank reduce-scatter body: this rank's dim-0 stripe of the
    cross-rank sum (the ring's reduce half; equivalence vs the old
    full-reduce-then-slice shape is pinned in tests/test_collectives.py)."""
    from jax import lax

    return lax.psum_scatter(t, axis, scatter_dimension=0, tiled=True)


# (cache_key, shape, dtype) -> compiled program. jit caches on callable
# identity, so the per-call closures below would otherwise retrace and
# recompile EVERY eager exchange — a per-step eager loop must pay trace
# + compile once per shape, then dispatch in microseconds. Bounded: an
# eager loop cycles a handful of shapes; evict oldest past the cap.
_EXCHANGE_CACHE: dict = {}
_EXCHANGE_CACHE_MAX = 64


def _run_over_process_mesh(body, cache_key, x, out_rows_per_proc: bool):
    """Run ``body(local_block)`` as one compiled SPMD program over the
    process mesh: each process contributes its local array as one shard
    of a stacked leading axis, takes back its own output block.
    ``cache_key`` names the exchange (op + static args) so same-shape
    calls reuse the compiled program."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.spmd import _SHARD_MAP_CHECK_KW, _shard_map

    mesh = _process_mesh()
    g = multihost_utils.host_local_array_to_global_array(x[None], mesh,
                                                         P("proc"))
    out_spec = P("proc")
    key = (cache_key, x.shape, str(x.dtype), mesh.shape["proc"])
    compiled = _EXCHANGE_CACHE.pop(key, None)  # pop+reinsert = LRU touch
    if compiled is None:
        def per_rank(t):
            return body(t[0], "proc")[None] if out_rows_per_proc else body(
                t[0], "proc")

        compiled = jax.jit(_shard_map(
            per_rank, mesh=mesh, in_specs=P("proc"), out_specs=out_spec,
            **{_SHARD_MAP_CHECK_KW: False}))
    _EXCHANGE_CACHE[key] = compiled
    while len(_EXCHANGE_CACHE) > _EXCHANGE_CACHE_MAX:
        _EXCHANGE_CACHE.pop(next(iter(_EXCHANGE_CACHE)))
    out = compiled(g)
    local = multihost_utils.global_array_to_host_local_array(out, mesh,
                                                             out_spec)
    return local[0] if out_rows_per_proc else local


def process_alltoall(x, split_axis: int = 0, concat_axis: int = 0):
    """Pairwise alltoall across processes: process p's split ``s`` of dim
    ``split_axis`` lands on process ``s``, received splits concatenate
    along ``concat_axis`` in source order — O(bytes) sent and received
    per rank (MPI_Alltoall's shape), vs the old allgather-then-select's
    O(size x bytes)."""
    x = jnp.asarray(x)
    return _run_over_process_mesh(
        lambda t, ax: _alltoall_on_axis(t, ax, split_axis, concat_axis),
        ("alltoall", split_axis, concat_axis), x, out_rows_per_proc=True)


def process_reducescatter(x):
    """Ring reduce-scatter across processes: each process receives its
    dim-0 stripe of the elementwise cross-process SUM — (n-1)/n of the
    tensor bytes per rank, vs the old full-reduce-then-slice's whole-
    tensor allreduce. Caller divides for the averaged variant."""
    x = jnp.asarray(x)
    return _run_over_process_mesh(_reducescatter_on_axis,
                                  ("reducescatter",), x,
                                  out_rows_per_proc=False)
