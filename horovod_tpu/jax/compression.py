"""Gradient compression for collective ops.

Parity with the reference compression module (horovod/torch/compression.py
and horovod/tensorflow/compression.py:33-74): a ``Compressor`` has
``compress(tensor) -> (tensor, ctx)`` and ``decompress(tensor, ctx)``;
``Compression.none`` and ``Compression.fp16`` match the reference, and
``Compression.bf16`` is the TPU-native addition (bfloat16 is the natural
reduced-precision wire format on TPU: full fp32 exponent range, so no
scale management, and ICI/MXU operate on it natively).
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (reference NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast to float16 before the collective, back after (reference
    FP16Compressor, tensorflow/compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """TPU-native: cast to bfloat16 on the wire."""

    wire_dtype = jnp.bfloat16


class Compression:
    """Optional gradient compression algorithm used during allreduce."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
