"""Gradient compression for collective ops.

Parity with the reference compression module (horovod/torch/compression.py
and horovod/tensorflow/compression.py:33-74): a ``Compressor`` has
``compress(tensor) -> (tensor, ctx)`` and ``decompress(tensor, ctx)``;
``Compression.none`` and ``Compression.fp16`` match the reference, and
``Compression.bf16`` is the TPU-native addition (bfloat16 is the natural
reduced-precision wire format on TPU: full fp32 exponent range, so no
scale management, and ICI/MXU operate on it natively).

Below the cast compressors sit the **low-bit wire codecs**
(``Compression.int8`` / ``Compression.fp8``): per-bucket absmax-scaled
quantization in the 1-bit-SGD / Deep-Gradient-Compression lineage, with
an error-feedback residual carried in optimizer state so the
quantization error of step ``t`` is re-injected at step ``t+1`` (Seide
et al. 2014; Lin et al. 2018). These apply ONLY to the inter-slice DCN
leg of the hierarchical bucket ladder (``HOROVOD_HIERARCHICAL``,
horovod_tpu/jax/fusion.py): the ICI legs stay at the gradients' own
dtype — ICI at 200 GB/s/chip is not the wall, DCN at ~3 GB/s/chip is
(tools/scaling_model.py). Their ``compress``/``decompress`` protocol
methods are identity (nothing is cast before bucketing); the
``quantize``/``dequantize`` classmethods are the DCN wire codec fusion
invokes per bucket shard. Without a hierarchical DCN leg they degrade
to lossless.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @classmethod
    def plan_dtype(cls, dtype):
        """The dtype a leaf of ``dtype`` enters the bucket plan with —
        what ``compress`` will hand ``fusion.plan_buckets``. Identity
        for everything except the cast compressors; static-accounting
        consumers (bench.py's wire stamp) use this so their plan can
        never drift from the executing one."""
        return dtype


class NoneCompressor(Compressor):
    """No-op (reference NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if cls.plan_dtype(dtype) != dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def plan_dtype(cls, dtype):
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return jnp.dtype(cls.wire_dtype)
        return dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast to float16 before the collective, back after (reference
    FP16Compressor, tensorflow/compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """TPU-native: cast to bfloat16 on the wire."""

    wire_dtype = jnp.bfloat16


class _ScaledQuantCompressor(Compressor):
    """Base for the low-bit DCN wire codecs: per-bucket absmax scaling.

    ``quantize(v) -> (payload, scale)`` maps a float tensor onto the
    wire dtype with one scalar scale (``absmax / cap``; zero-safe);
    ``dequantize(payload, scale)`` returns fp32. The Compressor
    protocol methods are identity — quantization happens per DCN-leg
    shard inside the hierarchical bucket ladder, never at bucketing
    time (the ICI legs stay full-dtype). ``dcn_wire`` marks the class
    for fusion's dispatch.
    """

    dcn_wire = True
    wire_dtype: jnp.dtype
    #: Largest representable magnitude of the wire dtype; absmax maps
    #: onto it so the payload spans the full quantization range.
    cap: float

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor

    @classmethod
    def quantize(cls, v):
        v = v.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(v))
        # Zero-safe: an all-zero shard quantizes to zeros at scale 1.
        scale = jnp.where(absmax > 0, absmax / cls.cap, 1.0)
        q = cls._encode(v / scale)
        return q, scale.astype(jnp.float32)

    @classmethod
    def dequantize(cls, payload, scale):
        return payload.astype(jnp.float32) * scale


class Int8Compressor(_ScaledQuantCompressor):
    """int8 DCN wire: symmetric linear quantization to [-127, 127]
    with a per-bucket-shard absmax scale (4x fewer wire bytes than
    fp32; error feedback makes the rounding error transient)."""

    wire_dtype = jnp.int8
    cap = 127.0

    @staticmethod
    def _encode(scaled):
        return jnp.clip(jnp.round(scaled), -127.0, 127.0).astype(jnp.int8)


class FP8Compressor(_ScaledQuantCompressor):
    """float8_e4m3 DCN wire: 4 exponent + 3 mantissa bits (~2 decimal
    digits, wider dynamic range than int8 at the same byte cost) —
    absmax-scaled into the format's finite range."""

    wire_dtype = jnp.float8_e4m3fn
    cap = 448.0  # float8_e4m3fn finite max

    @staticmethod
    def _encode(scaled):
        return jnp.clip(scaled, -448.0, 448.0).astype(jnp.float8_e4m3fn)


def is_dcn_wire(compression) -> bool:
    """True for the low-bit codecs that compress only the hierarchical
    DCN leg (int8/fp8) — fusion/optimizer dispatch on this."""
    return bool(getattr(compression, "dcn_wire", False))


class Compression:
    """Optional gradient compression algorithm used during allreduce."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor
