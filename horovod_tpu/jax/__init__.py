"""horovod_tpu.jax — the flagship framework binding.

Usage mirrors the reference bindings (e.g. ``import horovod.torch as hvd``,
reference examples/pytorch_synthetic_benchmark.py):

    import horovod_tpu.jax as hvd

    hvd.init()
    opt = hvd.DistributedOptimizer(optax.sgd(0.01))
    params = hvd.broadcast_parameters(params, root_rank=0)

    @hvd.spmd                      # every chip is a rank
    def train_step(params, batch):
        ...
        return hvd.allreduce(metric), new_params
"""

from horovod_tpu.common.basics import (
    check_extension,
    init,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_threads_supported,
    process_count,
    process_rank,
    rank,
    shutdown,
    size,
)
from horovod_tpu.jax.compression import Compression
from horovod_tpu.jax.mpi_ops import (
    Average,
    Handle,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allgatherv,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    allreduce_sparse,
    alltoall,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allreduce,
    poll,
    reducescatter,
    synchronize,
)
from horovod_tpu.jax.optimizer import (
    DistributedOptimizer,
    allreduce_gradients_transform,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    grad,
    value_and_grad,
)
from horovod_tpu.jax import zero
from horovod_tpu.jax.zero import sharded_distributed_optimizer
from horovod_tpu.jax import window
from horovod_tpu.jax.window import run_steps, windowed
from horovod_tpu.parallel.spmd import spmd, spmd_fn, spmd_run

# TF-parity aliases (reference tensorflow/__init__.py:95-115).
broadcast_variables = broadcast_parameters
broadcast_global_variables = broadcast_parameters

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "local_rank",
    "size",
    "local_size",
    "process_rank",
    "process_count",
    "mesh",
    "mpi_threads_supported",
    "check_extension",
    "allreduce",
    "allreduce_",
    "allreduce_async",
    "allreduce_async_",
    "allreduce_sparse",
    "grouped_allreduce",
    "allgather",
    "allgather_async",
    "allgatherv",
    "broadcast",
    "broadcast_",
    "broadcast_async",
    "broadcast_async_",
    "alltoall",
    "reducescatter",
    "poll",
    "synchronize",
    "Handle",
    "Sum",
    "Average",
    "Min",
    "Max",
    "Product",
    "Compression",
    "DistributedOptimizer",
    "allreduce_gradients_transform",
    "grad",
    "value_and_grad",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "broadcast_object",
    "broadcast_variables",
    "broadcast_global_variables",
    "spmd",
    "spmd_fn",
    "spmd_run",
    "zero",
    "sharded_distributed_optimizer",
    "window",
    "run_steps",
    "windowed",
]
