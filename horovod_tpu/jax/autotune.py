"""Step-time autotuning for the XLA/SPMD lane (HOROVOD_AUTOTUNE).

The reference autotuner tuned {fusion threshold, cycle time} against
bytes/sec scored over sampling windows (horovod/common/parameter_manager.h:
35-43,149-217). On the compiled SPMD lane there is no cycle time — the only
knob with a data-plane meaning is the gradient-bucket fusion threshold used
by :mod:`horovod_tpu.jax.fusion` — and the honest objective is measured
step wall-time, since bucketing trades ICI launch latency against
concatenate/slice overhead inside one XLA program.

Mechanism: :func:`horovod_tpu.parallel.spmd.spmd_fn` dispatch handles
consult this tuner. Every ``window`` steps the tuner blocks on the step
output (the only way to observe real device time under async dispatch),
scores the current threshold in steps/sec, advances to the next candidate,
and bumps ``generation`` — which makes every dispatch handle re-jit so the
new threshold re-traces into a new bucket plan. Per candidate the first
window is discarded as warmup (it pays the recompile), mirroring the
reference's warmup-discard (parameter_manager.h:38-43). After one sweep the
best threshold wins, ``converged`` flips, and the hot path never blocks
again. Scores append to HOROVOD_AUTOTUNE_LOG in the same TSV layout as the
native tuner (csrc/autotune/parameter_manager.cc).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence


# Sweep space: "no fusion" plus power-of-two thresholds spanning the
# reference's 0..64 MB range (parameter_manager.cc search space) one octave
# past it, since TPU gradient sets can exceed 64 MB.
DEFAULT_CANDIDATES = [0] + [1 << s for s in range(20, 28)]  # 1 MB .. 128 MB


class StepAutotuner:
    """Sweeps ``config.fusion_threshold`` against measured step rate."""

    def __init__(
        self,
        config,
        log_path: str = "",
        candidates: Optional[Sequence[int]] = None,
        window: int = 10,
    ) -> None:
        self.config = config
        cand = list(candidates if candidates is not None else DEFAULT_CANDIDATES)
        # Sweep the CURRENT (default) threshold first: if tuning ever
        # stalls (e.g. no handle keeps dispatching), the job is left at
        # the untuned default rather than at an arbitrary candidate.
        self.candidates: List[int] = [config.fusion_threshold] + [
            c for c in cand if c != config.fusion_threshold
        ]
        self.window = max(1, int(window))
        self.generation = 1
        self.converged = False
        self.best_threshold = config.fusion_threshold
        self.best_score = -1.0
        self._idx = 0
        self._warming = True
        self._steps_in_window = 0
        self._t0: Optional[float] = None
        self._samples = 0
        self._owner = None
        self._owner_idle = 0
        self._log = open(log_path, "w") if log_path else None
        config.fusion_threshold = self.candidates[0]

    # -- dispatch-side hooks ------------------------------------------------

    def claim(self, handle) -> bool:
        """Bind the tuner to ONE dispatch handle at a time. Only the
        owner's steps are counted/scored; a second SPMD handle in the loop
        (eval step, metric reduction) would otherwise pollute the
        steps/sec score with a different program. If the owner stops
        dispatching (a warmup/eval handle that claimed first, a rebuilt
        step), ownership hands off to the active handle after 3 windows
        of owner inactivity and the partial window restarts — the sweep
        can slow down but never stalls. Both claim and handoff follow
        dispatch order, which is program order, so every process makes
        identical decisions."""
        if self._owner is None or handle is self._owner:
            self._owner = handle
            self._owner_idle = 0
            return True
        self._owner_idle += 1
        if self._owner_idle > 3 * self.window:
            self._owner = handle
            self._owner_idle = 0
            self._steps_in_window = 0
            self._warming = True
            self._t0 = None
            return True
        return False

    def step_done(self) -> bool:
        """Count one dispatched step; True when the caller must block on the
        step output and call :meth:`end_window`."""
        if self.converged:
            return False
        self._steps_in_window += 1
        return self._steps_in_window >= self.window

    def end_window(self) -> None:
        """Score the window that just completed (caller has synced)."""
        now = time.perf_counter()
        self._steps_in_window = 0
        if self._warming or self._t0 is None:
            # Warmup window: paid the recompile for this candidate.
            self._log_line("warmup", self.config.fusion_threshold, 0.0)
            self._warming = False
            self._t0 = now
            return
        score = self.window / (now - self._t0)  # steps/sec
        self._log_line("sample", self.config.fusion_threshold, score)
        if score > self.best_score:
            self.best_score = score
            self.best_threshold = self.config.fusion_threshold
        self._idx += 1
        if self._idx >= len(self.candidates):
            self._sync_winner()
            self.config.fusion_threshold = self.best_threshold
            self.converged = True
            self.generation += 1
            # Only process 0 has a log (basics gates log_path), and
            # process 0 is the sync root, so its winner — and therefore
            # this score — is always its own measurement.
            self._log_line("converged", self.best_threshold, self.best_score)
            if self._log is not None:
                self._log.close()
                self._log = None
        else:
            self.config.fusion_threshold = self.candidates[self._idx]
            self.generation += 1
            self._warming = True
            self._t0 = now

    def _sync_winner(self) -> bool:
        """Multi-host: adopt process 0's winner so every process re-traces
        the SAME bucket plan. Local timing noise can rank candidates
        differently per host; divergent plans would lower different
        collective sequences into the "same" SPMD program. The reference
        broadcast tuned params from rank 0 for the same reason
        (horovod/common/parameter_manager.h:95-96,232). Returns True when
        the local winner was overridden."""
        from horovod_tpu.common.state import global_state

        st = global_state()
        if st.process_count <= 1:
            return False
        import jax.numpy as jnp

        from horovod_tpu.jax import eager

        won = int(
            eager.process_broadcast(
                jnp.asarray([self.best_threshold], jnp.int32), 0
            )[0]
        )
        overridden = won != self.best_threshold
        self.best_threshold = won
        return overridden

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- logging ------------------------------------------------------------

    def _log_line(self, kind: str, threshold: int, score: float) -> None:
        self._samples += 1
        if self._log is not None:
            # Same TSV columns as the native tuner's log
            # (csrc/autotune/parameter_manager.cc): sample index, kind,
            # threshold bytes, cycle ms (n/a on this lane), score.
            self._log.write(
                f"{self._samples}\t{kind}\t{threshold}\t0.0\t{score}\n"
            )
            self._log.flush()
