"""Step-time autotuning for the XLA/SPMD lane (HOROVOD_AUTOTUNE).

The reference autotuner tuned {fusion threshold, cycle time} against
bytes/sec scored over sampling windows (horovod/common/parameter_manager.h:
35-43,149-217). On the compiled SPMD lane there is no cycle time — the only
knob with a data-plane meaning is the gradient-bucket fusion threshold used
by :mod:`horovod_tpu.jax.fusion` — and the honest objective is measured
step wall-time, since bucketing trades ICI launch latency against
concatenate/slice overhead inside one XLA program.

Mechanism: :func:`horovod_tpu.parallel.spmd.spmd_fn` dispatch handles
consult this tuner. Every ``window`` steps the tuner blocks on the step
output (the only way to observe real device time under async dispatch),
scores the current threshold in steps/sec, advances to the next candidate,
and bumps ``generation`` — which makes every dispatch handle re-jit so the
new threshold re-traces into a new bucket plan. Per candidate the first
window is discarded as warmup (it pays the recompile), mirroring the
reference's warmup-discard (parameter_manager.h:38-43). Candidate order
comes from the native GP + expected-improvement machinery when available
(``hvdtpu_ei_next`` — the same csrc/autotune/ code that tunes the eager
lane, reference bayesian_optimization.h:31-44), else a sequential sweep;
scores are synced from process 0 so every process probes and converges
identically. When probing ends the best threshold wins, ``converged``
flips, and the hot path never blocks again. Scores append to
HOROVOD_AUTOTUNE_LOG in the same TSV layout as the native tuner
(csrc/autotune/parameter_manager.cc).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence


# Sweep space: "no fusion" plus power-of-two thresholds spanning the
# reference's 0..64 MB range (parameter_manager.cc search space) one octave
# past it, since TPU gradient sets can exceed 64 MB.
DEFAULT_CANDIDATES = [0] + [1 << s for s in range(20, 28)]  # 1 MB .. 128 MB


class StepAutotuner:
    """Tunes ``config.fusion_threshold`` against measured step rate.

    ``strategy``: ``"sweep"`` probes every candidate in order; ``"ei"``
    probes 3 seeds (current default, largest, middle) and then lets the
    native GP + expected-improvement machinery (csrc/autotune/, the same
    code that tunes the eager lane) pick each next probe, stopping at
    ``max_probes`` — roughly half the windows of a full sweep on the
    default 9-candidate space. ``"auto"`` (default) uses EI when the
    native library is available and the candidate space is big enough to
    be worth a surrogate, else sweeps. Multi-host, process 0 alone picks
    candidates and broadcasts each decision, so probe sequences cannot
    diverge across hosts.
    """

    def __init__(
        self,
        config,
        log_path: str = "",
        candidates: Optional[Sequence[int]] = None,
        window: int = 10,
        strategy: str = "auto",
        max_probes: Optional[int] = None,
    ) -> None:
        self.config = config
        cand = list(candidates if candidates is not None else DEFAULT_CANDIDATES)
        # Probe the CURRENT (default) threshold first: if tuning ever
        # stalls (e.g. no handle keeps dispatching), the job is left at
        # the untuned default rather than at an arbitrary candidate.
        self.candidates: List[int] = [config.fusion_threshold] + [
            c for c in cand if c != config.fusion_threshold
        ]
        self.window = max(1, int(window))
        self.strategy = strategy
        self.max_probes = max_probes or (
            3 + (len(self.candidates) - 3 + 1) // 2
        )
        self.generation = 1
        self.converged = False
        self.best_threshold = config.fusion_threshold
        self.best_score = -1.0
        self.probed: dict = {}  # threshold -> synced score
        # Resolve the strategy NOW (setup time, where a cold native build
        # is acceptable) rather than mid-training. Only process 0's
        # strategy matters: it alone picks candidates; everyone else
        # follows its broadcast decisions, so per-host differences in
        # native availability cannot diverge the probe sequence.
        if strategy == "auto":
            if len(self.candidates) >= 5:
                try:
                    from horovod_tpu import native

                    native.load_library()
                    strategy = "ei"
                except Exception:
                    strategy = "sweep"
            else:
                strategy = "sweep"
        self._strategy_resolved = strategy
        self._warming = True
        self._steps_in_window = 0
        self._t0: Optional[float] = None
        self._samples = 0
        self._owner = None
        self._owner_idle = 0
        self._log = open(log_path, "w") if log_path else None
        config.fusion_threshold = self.candidates[0]

    # -- dispatch-side hooks ------------------------------------------------

    def claim(self, handle) -> bool:
        """Bind the tuner to ONE dispatch handle at a time. Only the
        owner's steps are counted/scored; a second SPMD handle in the loop
        (eval step, metric reduction) would otherwise pollute the
        steps/sec score with a different program. If the owner stops
        dispatching (a warmup/eval handle that claimed first, a rebuilt
        step), ownership hands off to the active handle after 3 windows
        of owner inactivity and the partial window restarts — the sweep
        can slow down but never stalls. Both claim and handoff follow
        dispatch order, which is program order, so every process makes
        identical decisions."""
        if self._owner is None or handle is self._owner:
            self._owner = handle
            self._owner_idle = 0
            return True
        self._owner_idle += 1
        if self._owner_idle > 3 * self.window:
            self._owner = handle
            self._owner_idle = 0
            self._steps_in_window = 0
            self._warming = True
            self._t0 = None
            return True
        return False

    def step_done(self) -> bool:
        """Count one dispatched step; True when the caller must block on the
        step output and call :meth:`end_window`."""
        if self.converged:
            return False
        self._steps_in_window += 1
        return self._steps_in_window >= self.window

    def end_window(self) -> None:
        """Score the window that just completed (caller has synced)."""
        now = time.perf_counter()
        self._steps_in_window = 0
        if self._warming or self._t0 is None:
            # Warmup window: paid the recompile for this candidate.
            self._log_line("warmup", self.config.fusion_threshold, 0.0)
            self._warming = False
            self._t0 = now
            return
        score = self.window / (now - self._t0)  # steps/sec
        # Multi-host: every process adopts process 0's measurement, so
        # probed/best — and therefore every EI decision and the final
        # winner — are identical everywhere. Divergent bucket plans
        # would lower different collective sequences into the "same"
        # SPMD program (reference SyncParams rationale,
        # parameter_manager.h:95-96,232).
        score = self._sync_value(score)
        self.probed[self.config.fusion_threshold] = score
        self._log_line("sample", self.config.fusion_threshold, score)
        if score > self.best_score:
            self.best_score = score
            self.best_threshold = self.config.fusion_threshold
        nxt = self._decide_next()
        if nxt is None:
            self.config.fusion_threshold = self.best_threshold
            self.converged = True
            self.generation += 1
            self._log_line("converged", self.best_threshold, self.best_score)
            if self._log is not None:
                self._log.close()
                self._log = None
        else:
            self.config.fusion_threshold = nxt
            self.generation += 1
            self._warming = True
            self._t0 = now

    # -- candidate selection ------------------------------------------------

    @staticmethod
    def _xform(threshold: int) -> float:
        """Thresholds live on a log scale (0, 1 MB .. 128 MB); the GP
        surrogate sees log2(1 + MB) so candidates are evenly spaced."""
        import math

        return math.log2(1.0 + threshold / float(1 << 20))

    def _decide_next(self) -> Optional[int]:
        """Process 0 picks the next probe; everyone adopts its choice.
        One broadcast decision per window makes divergence structurally
        impossible — no local EI result, native-build failure, or FP
        difference can fork the probe sequence across hosts."""
        from horovod_tpu.common.state import global_state

        st = global_state()
        if st.process_count <= 1:
            return self._next_candidate()
        import jax.numpy as jnp

        from horovod_tpu.jax import eager

        local = -1
        if st.process_index == 0:
            nxt = self._next_candidate()
            local = -1 if nxt is None else int(nxt)
        got = int(
            eager.process_broadcast(jnp.asarray([local], jnp.int32), 0)[0]
        )
        return None if got < 0 else got

    def _next_candidate(self) -> Optional[int]:
        unprobed = [c for c in self.candidates if c not in self.probed]
        if not unprobed:
            return None
        if self._strategy_resolved == "sweep":
            return unprobed[0]
        if len(self.probed) >= self.max_probes:
            return None
        # Seeds: default (already probed first), largest, middle.
        for seed in (self.candidates[-1],
                     self.candidates[len(self.candidates) // 2]):
            if seed not in self.probed:
                return seed
        try:
            from horovod_tpu import native

            i = native.ei_next(
                [self._xform(t) for t in self.probed],
                list(self.probed.values()),
                [self._xform(c) for c in unprobed],
            )
            if i >= 0:
                return unprobed[i]
        except Exception:
            pass
        return unprobed[0]

    def _sync_value(self, value: float) -> float:
        """Adopt process 0's measurement (identity on one process)."""
        from horovod_tpu.common.state import global_state

        st = global_state()
        if st.process_count <= 1:
            return value
        import jax.numpy as jnp

        from horovod_tpu.jax import eager

        return float(
            eager.process_broadcast(
                jnp.asarray([value], jnp.float32), 0
            )[0]
        )

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- logging ------------------------------------------------------------

    def _log_line(self, kind: str, threshold: int, score: float) -> None:
        self._samples += 1
        if self._log is not None:
            # Same TSV columns as the native tuner's log
            # (csrc/autotune/parameter_manager.cc): sample index, kind,
            # threshold bytes, cycle ms (n/a on this lane), score.
            self._log.write(
                f"{self._samples}\t{kind}\t{threshold}\t0.0\t{score}\n"
            )
            self._log.flush()
