"""Step-time autotuning for the XLA/SPMD lane (HOROVOD_AUTOTUNE).

The reference autotuner tuned {fusion threshold, cycle time} NUMERICALLY
and the hierarchical-allreduce/allgather modes CATEGORICALLY against
bytes/sec scored over sampling windows (horovod/common/parameter_manager.
h:35-43,149-217 — CategoricalParameterChain wrapping the numeric
Bayesian chain). On the compiled SPMD lane there is no cycle time — the
knobs with a data-plane meaning are the gradient-bucket fusion threshold
used by :mod:`horovod_tpu.jax.fusion` and the hierarchical-allreduce
routing (two-level ICI/DCN ladder vs flat psum) — and the honest
objective is measured step wall-time.

Mechanism: :func:`horovod_tpu.parallel.spmd.spmd_fn` dispatch handles
consult this tuner. Every ``window`` steps the tuner blocks on the step
output (the only way to observe real device time under async dispatch),
scores the current candidate in steps/sec, advances to the next, and
bumps ``generation`` — which makes every dispatch handle re-jit so the
new (threshold, hierarchical) pair re-traces into a new bucket/collective
plan. Per candidate the first window is discarded as warmup (it pays the
recompile), mirroring the reference's warmup-discard
(parameter_manager.h:38-43). Candidate order comes from the native GP +
expected-improvement machinery when available (``hvdtpu_ei_next`` — the
same csrc/autotune/ code that tunes the eager lane, reference
bayesian_optimization.h:31-44) run per hierarchical category, else a
sequential sweep; scores are synced from process 0 so every process
probes and converges identically. When probing ends the best
(threshold, hierarchical) pair wins, ``converged`` flips, and the hot
path never blocks again. Scores append to HOROVOD_AUTOTUNE_LOG in the
native tuner's TSV layout plus a hierarchical column.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple


# Sweep space: "no fusion" plus power-of-two thresholds spanning the
# reference's 0..64 MB range (parameter_manager.cc search space) one octave
# past it, since TPU gradient sets can exceed 64 MB.
DEFAULT_CANDIDATES = [0] + [1 << s for s in range(20, 28)]  # 1 MB .. 128 MB

Candidate = Tuple[int, bool]  # (fusion_threshold bytes, hierarchical)


def _hier_available(st) -> bool:
    """Whether the two-level ladder can tile the "hvd" axis — delegated
    to fusion.py's own resolution (the SAME resolve_hierarchical the
    traced collective runs, slice detection included) so the tuner's
    candidate space and the executing path can never drift apart."""
    from horovod_tpu.jax.fusion import resolve_hierarchical

    return resolve_hierarchical("on", st.global_device_count) > 0


class StepAutotuner:
    """Tunes ``config.fusion_threshold`` and
    ``config.hierarchical_allreduce`` against measured step rate.

    ``candidates`` accepts plain thresholds (tuned flat-only, the
    original surface) or ``(threshold, hierarchical)`` pairs. By default
    the space is every threshold in flat mode plus — when the mesh can
    actually ladder — every threshold in hierarchical mode, mirroring
    the reference's categorical x numeric joint space
    (parameter_manager.h:149-205).

    ``strategy``: ``"sweep"`` probes every candidate in order; ``"ei"``
    probes 3 seeds and then lets the native GP + expected-improvement
    machinery pick each next probe WITHIN a hierarchical category,
    alternating between categories that still have unprobed candidates,
    stopping at ``max_probes``. ``"auto"`` (default) uses EI when the
    native library is available and the candidate space is big enough to
    be worth a surrogate, else sweeps. Multi-host, process 0 alone picks
    candidates and broadcasts each decision, so probe sequences cannot
    diverge across hosts.
    """

    def __init__(
        self,
        config,
        log_path: str = "",
        candidates: Optional[Sequence] = None,
        window: int = 10,
        strategy: str = "auto",
        max_probes: Optional[int] = None,
    ) -> None:
        self.config = config
        if candidates is not None:
            cand = [c if isinstance(c, tuple) else (int(c), False)
                    for c in candidates]
        else:
            cand = [(t, False) for t in DEFAULT_CANDIDATES]
            from horovod_tpu.common.state import global_state

            if _hier_available(global_state()):
                cand += [(t, True) for t in DEFAULT_CANDIDATES]
        # Probe the CURRENT (default) setting first: if tuning ever
        # stalls (e.g. no handle keeps dispatching), the job is left at
        # the untuned default rather than at an arbitrary candidate.
        current: Candidate = (config.fusion_threshold,
                              bool(config.hierarchical_allreduce))
        self.candidates: List[Candidate] = [current] + [
            c for c in cand if c != current
        ]
        self.window = max(1, int(window))
        self.strategy = strategy
        self.max_probes = max_probes or (
            3 + (len(self.candidates) - 3 + 1) // 2
        )
        self.generation = 1
        self.converged = False
        self.best_threshold = current[0]
        self.best_hierarchical = current[1]
        self.best_score = -1.0
        self.probed: dict = {}  # (threshold, hier) -> synced score
        # Resolve the strategy NOW (setup time, where a cold native build
        # is acceptable) rather than mid-training. Only process 0's
        # strategy matters: it alone picks candidates; everyone else
        # follows its broadcast decisions, so per-host differences in
        # native availability cannot diverge the probe sequence.
        if strategy == "auto":
            if len(self.candidates) >= 5:
                try:
                    from horovod_tpu import native

                    native.load_library()
                    strategy = "ei"
                except Exception:
                    strategy = "sweep"
            else:
                strategy = "sweep"
        self._strategy_resolved = strategy
        self._ei_category = False  # alternates when both have unprobed
        self._warming = True
        self._steps_in_window = 0
        self._t0: Optional[float] = None
        self._samples = 0
        self._owner = None
        self._owner_idle = 0
        self._log = open(log_path, "w") if log_path else None
        self._apply(self.candidates[0])

    def _apply(self, cand: Candidate) -> None:
        self.config.fusion_threshold = cand[0]
        self.config.hierarchical_allreduce = cand[1]
        # Pin the tri-state knob too: without this, a FLAT candidate on
        # a DCN-present mesh would still ladder through the default
        # "auto" (fusion.resolve_hierarchical) and the categorical A/B
        # would silently probe ladder-vs-ladder.
        self.config.hierarchical = "on" if cand[1] else "off"

    def _current(self) -> Candidate:
        return (self.config.fusion_threshold,
                bool(self.config.hierarchical_allreduce))

    # -- dispatch-side hooks ------------------------------------------------

    def claim(self, handle) -> bool:
        """Bind the tuner to ONE dispatch handle at a time. Only the
        owner's steps are counted/scored; a second SPMD handle in the loop
        (eval step, metric reduction) would otherwise pollute the
        steps/sec score with a different program. If the owner stops
        dispatching (a warmup/eval handle that claimed first, a rebuilt
        step), ownership hands off to the active handle after 3 windows
        of owner inactivity and the partial window restarts — the sweep
        can slow down but never stalls. Both claim and handoff follow
        dispatch order, which is program order, so every process makes
        identical decisions."""
        if self._owner is None or handle is self._owner:
            self._owner = handle
            self._owner_idle = 0
            return True
        self._owner_idle += 1
        if self._owner_idle > 3 * self.window:
            self._owner = handle
            self._owner_idle = 0
            self._steps_in_window = 0
            self._warming = True
            self._t0 = None
            return True
        return False

    def step_done(self) -> bool:
        """Count one dispatched step; True when the caller must block on the
        step output and call :meth:`end_window`."""
        if self.converged:
            return False
        self._steps_in_window += 1
        return self._steps_in_window >= self.window

    def end_window(self, out=None) -> None:
        """Score the window that just completed.

        ``out`` is the window's step output: when given, the tuner itself
        enforces the forced-d2h-sync discipline of ``bench.py:_force_sync``
        (shared impl: :func:`horovod_tpu.utils.devsync.force_device_sync`)
        BEFORE reading the clock. On the tunneled backend a bare
        ``block_until_ready`` does not observe device completion until the
        process's first device->host pull — exactly the round-5
        measurement trap (VERDICT round-5 weak #4) — so a probe that only
        blocked would score dispatch rate, not step rate, and converge to
        a meaningless winner. ``out=None`` keeps the legacy contract
        (caller has already synced for real).
        """
        if out is not None:
            from horovod_tpu.utils.devsync import window_sync

            # block_until_ready + the d2h pull that makes the block real.
            window_sync(out)
        now = time.perf_counter()
        self._steps_in_window = 0
        if self._warming or self._t0 is None:
            # Warmup window: paid the recompile for this candidate.
            self._log_line("warmup", self._current(), 0.0)
            self._warming = False
            self._t0 = now
            return
        score = self.window / (now - self._t0)  # steps/sec
        # Multi-host: every process adopts process 0's measurement, so
        # probed/best — and therefore every EI decision and the final
        # winner — are identical everywhere. Divergent bucket plans
        # would lower different collective sequences into the "same"
        # SPMD program (reference SyncParams rationale,
        # parameter_manager.h:95-96,232).
        score = self._sync_value(score)
        cur = self._current()
        self.probed[cur] = score
        self._log_line("sample", cur, score)
        if score > self.best_score:
            self.best_score = score
            self.best_threshold, self.best_hierarchical = cur
        nxt = self._decide_next()
        if nxt is None:
            self._apply((self.best_threshold, self.best_hierarchical))
            self.converged = True
            self.generation += 1
            self._log_line(
                "converged",
                (self.best_threshold, self.best_hierarchical),
                self.best_score)
            if self._log is not None:
                self._log.close()
                self._log = None
        else:
            self._apply(nxt)
            self.generation += 1
            self._warming = True
            self._t0 = now

    # -- candidate selection ------------------------------------------------

    @staticmethod
    def _xform(threshold: int) -> float:
        """Thresholds live on a log scale (0, 1 MB .. 128 MB); the GP
        surrogate sees log2(1 + MB) so candidates are evenly spaced."""
        import math

        return math.log2(1.0 + threshold / float(1 << 20))

    def _decide_next(self) -> Optional[Candidate]:
        """Process 0 picks the next probe; everyone adopts its choice.
        One broadcast decision per window makes divergence structurally
        impossible — no local EI result, native-build failure, or FP
        difference can fork the probe sequence across hosts."""
        from horovod_tpu.common.state import global_state

        st = global_state()
        if st.process_count <= 1:
            return self._next_candidate()
        import jax.numpy as jnp

        from horovod_tpu.jax import eager

        local = [-1, 0]
        if st.process_index == 0:
            nxt = self._next_candidate()
            if nxt is not None:
                local = [int(nxt[0]), int(nxt[1])]
        # int32 is enough: thresholds cap at 128 MB << 2^31.
        got = eager.process_broadcast(jnp.asarray(local, jnp.int32), 0)
        t = int(got[0])
        return None if t < 0 else (t, bool(int(got[1])))

    def _next_candidate(self) -> Optional[Candidate]:
        unprobed = [c for c in self.candidates if c not in self.probed]
        if not unprobed:
            return None
        if self._strategy_resolved == "sweep":
            return unprobed[0]
        if len(self.probed) >= self.max_probes:
            return None
        # Seeds: default (already probed first), largest flat, then —
        # when the space has a hierarchical category — the mid
        # hierarchical candidate, else the mid flat one.
        flats = [c for c in self.candidates if not c[1]]
        hiers = [c for c in self.candidates if c[1]]
        seeds = []
        if flats:
            seeds.append(flats[-1])
        if hiers:
            seeds.append(hiers[len(hiers) // 2])
        elif flats:
            seeds.append(flats[len(flats) // 2])
        for seed in seeds:
            if seed not in self.probed and seed in unprobed:
                return seed
        # EI within a category; alternate between categories that still
        # have unprobed candidates so both hierarchy modes keep getting
        # explored (the reference swept its categorical chain similarly).
        for _ in range(2):
            self._ei_category = not self._ei_category
            pool = [c for c in unprobed if c[1] == self._ei_category]
            if pool:
                break
        else:
            pool = unprobed
        if not pool:
            return unprobed[0]
        known = [(k, v) for k, v in self.probed.items()
                 if k[1] == pool[0][1]]
        if len(known) >= 2:
            try:
                from horovod_tpu import native

                i = native.ei_next(
                    [self._xform(k[0]) for k, _ in known],
                    [v for _, v in known],
                    [self._xform(c[0]) for c in pool],
                )
                if i >= 0:
                    return pool[i]
            except Exception:
                pass
        return pool[0]

    def _sync_value(self, value: float) -> float:
        """Adopt process 0's measurement (identity on one process)."""
        from horovod_tpu.common.state import global_state

        st = global_state()
        if st.process_count <= 1:
            return value
        import jax.numpy as jnp

        from horovod_tpu.jax import eager

        return float(
            eager.process_broadcast(
                jnp.asarray([value], jnp.float32), 0
            )[0]
        )

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- logging ------------------------------------------------------------

    def _log_line(self, kind: str, cand: Candidate, score: float) -> None:
        self._samples += 1
        if self._log is not None:
            # The native tuner's TSV columns (csrc/autotune/
            # parameter_manager.cc) — sample index, kind, threshold
            # bytes, cycle ms (n/a on this lane), score — plus a sixth
            # hierarchical column (0/1).
            self._log.write(
                f"{self._samples}\t{kind}\t{cand[0]}\t0.0\t{score}"
                f"\t{int(cand[1])}\n"
            )
            self._log.flush()
