"""ZeRO-1 optimizer-state sharding over the data-parallel axis.

The reference framework replicates optimizer state on every rank and
allreduces full gradients (horovod/torch/__init__.py:95-151). On a TPU
mesh the same bytes can carry more information: a reduce-scatter delivers
each rank the *sum* of 1/N of the gradient for half the cost of a full
allreduce, each rank updates only its 1/N slice of the optimizer state,
and an all-gather of the updated slice completes the step. Total wire
traffic per step is identical to one allreduce (reduce-scatter +
all-gather is exactly how a ring allreduce decomposes), but optimizer
state memory and update FLOPs drop by the axis size. This is the ZeRO
stage-1 partitioning (Rajbhandari et al., 2020) expressed as XLA
collectives; the reference has no counterpart (it predates ZeRO), so this
is a TPU-first extension, not a parity item.

Design (idiomatic shard_map, no runtime coordination):

* ``sharded_distributed_optimizer(opt)`` is an ``optax``
  GradientTransformation, drop-in where :func:`DistributedOptimizer` fits.
* ``init`` (outside the SPMD region) builds the optimizer state over ONE
  flat padded vector per parameter dtype — its leaves have *global* shape
  ``(pad,)``. Fed into the training step with ``P("hvd")`` partition
  specs, shard_map gives each rank its ``(pad/N,)`` slice: the state is
  physically sharded across chips, never materialized whole on any one.
* ``update`` (inside the SPMD region): flatten grads per dtype,
  ``lax.psum_scatter`` (the reduce-scatter phase of the ring), update the
  local shard with the wrapped optimizer, ``lax.all_gather`` the updated
  slice back to full parameter updates.
* :func:`state_partition_specs` derives the ``P("hvd")``-vs-replicated
  spec tree for a state containing :class:`ZeroState` nodes, so wiring
  the sharding into ``spmd_fn(in_specs=..., out_specs=...)`` is one call.

Constraint: the wrapped optimizer must be *elementwise* (sgd, momentum,
adam, adamw, rmsprop, ...). Transforms that mix information across
parameters (``clip_by_global_norm``, layer-wise trust ratios) would see
only the local shard; compose those *outside* this wrapper.

Note on ZeRO stage 2 (gradient-shard persistence): under XLA the full
gradient exists only transiently inside the one-step program — XLA frees
the flat gradient buffer after the reduce-scatter consumes it, and
nothing persists between steps except params and the (sharded) optimizer
state. Stage 2's benefit over stage 1 is therefore automatic here; there
is no resident gradient buffer to shard.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common import basics
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.parallel.logical import module_axis


class ZeroState:
    """Optimizer state for the sharded optimizer.

    ``inner`` is the wrapped optimizer's state over ``{dtype_key: flat}``
    vectors; ``pads`` maps dtype key -> padded global flat length (static
    metadata, carried in the pytree structure so partition-spec derivation
    and donation both see it).
    """

    def __init__(self, inner: Any, pads: Dict[str, int]):
        self.inner = inner
        self.pads = dict(pads)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ZeroState(pads={self.pads}, inner={self.inner!r})"


jax.tree_util.register_pytree_node(
    ZeroState,
    lambda s: ((s.inner,), tuple(sorted(s.pads.items()))),
    lambda aux, children: ZeroState(children[0], dict(aux)),
)


def _dtype_key(dt) -> str:
    return str(jnp.dtype(dt))


def _group_by_dtype(leaves) -> Dict[str, List[int]]:
    """Leaf indices grouped by dtype, insertion-ordered within a group."""
    groups: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(_dtype_key(leaf.dtype), []).append(i)
    return groups


def _pad_to(total: int, n: int) -> int:
    return ((total + n - 1) // n) * n


def _flatten_group(leaves, idxs, pad: int):
    flat = (
        jnp.concatenate([leaves[i].ravel() for i in idxs])
        if len(idxs) > 1
        else leaves[idxs[0]].ravel()
    )
    if flat.size < pad:
        flat = jnp.pad(flat, (0, pad - flat.size))
    return flat


def _split_group(flat, leaves, idxs, out: list) -> None:
    offset = 0
    for i in idxs:
        sz = leaves[i].size
        out[i] = flat[offset : offset + sz].reshape(leaves[i].shape)
        offset += sz


def sharded_distributed_optimizer(
    optimizer: optax.GradientTransformation,
    average: bool = True,
    axis_name: Optional[str] = None,
    compression=None,
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` with ZeRO-1 sharding over the ``axis_name`` mesh
    axis. See the module docstring for semantics.

    ``compression`` (e.g. ``Compression.fp16``) applies to the
    reduce-scatter wire, the analogue of the reference compressing the
    allreduce wire (horovod/tensorflow/compression.py:46-64); the
    all-gather of updates stays in the update dtype.

    With one rank this degrades to a flat-vector local update (identical
    results to the unwrapped optimizer); the multi-process eager lane is
    unsupported (the SPMD lane is where sharding pays). Multi-host jobs
    must build the training step with ``spmd_fn(..., host_local=False)``
    and carry global jax.Arrays — the state's flat vectors are global,
    not per-host shards, and update() rejects the default host-local
    conversion with a clear error.
    """
    from horovod_tpu.jax.compression import Compression

    axis_name = module_axis("data", axis_name)
    if compression is None:
        compression = Compression.none

    def init_fn(params):
        st = global_state()
        st.require_init()
        n = basics.size()
        leaves = jax.tree_util.tree_leaves(params)
        groups = _group_by_dtype(leaves)
        pads = {
            key: _pad_to(sum(leaves[i].size for i in idxs), n)
            for key, idxs in groups.items()
        }
        # Global-shaped flat zeros; sharded physically by the P(axis) specs
        # the caller attaches (state_partition_specs).
        flats = {
            key: jnp.zeros((pads[key],), dtype=jnp.dtype(key))
            for key in sorted(groups)
        }
        return ZeroState(optimizer.init(flats), pads)

    def update_fn(updates, state: ZeroState, params=None):
        axis = current_spmd_axis()
        if axis is None:
            # Hand-built shard_map (not via hvd.spmd_run/spmd_fn): the
            # harness context is unset, but ``axis_name`` may still be a
            # live mesh axis in this trace — honor it, so ZeRO composes
            # with custom multi-axis meshes (e.g. ZeRO over "dp" inside a
            # {dp, sp} shard_map; test_parallel_lm.py).
            try:
                lax.axis_size(axis_name)
                axis = axis_name
            except NameError:
                axis = None
        st = global_state()
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        groups = _group_by_dtype(leaves)
        if set(state.pads) != set(groups):
            raise ValueError(
                f"gradient dtypes {sorted(groups)} do not match the dtypes "
                f"this optimizer state was initialized with "
                f"{sorted(state.pads)}"
            )
        p_leaves = (
            jax.tree_util.tree_leaves(params) if params is not None else None
        )

        if axis is None:
            if st.process_count > 1:
                raise NotImplementedError(
                    "sharded_distributed_optimizer requires the SPMD lane "
                    "(hvd.spmd_run/spmd_fn); the multi-process eager lane "
                    "keeps optimizer state replicated — use "
                    "DistributedOptimizer there."
                )
            n = 1
        else:
            if st.process_count > 1 and getattr(
                st, "dispatch_host_local", True
            ):
                raise ValueError(
                    "ZeRO optimizer state holds GLOBAL-shaped flat vectors, "
                    "but this multi-host spmd_fn was built with the default "
                    "host_local=True, which would treat them as per-host "
                    "shards and concatenate them. Build the training step "
                    "with hvd.spmd_fn(..., host_local=False) and keep "
                    "global jax.Arrays across steps."
                )
            axis = axis_name  # shard over OUR axis (may differ from the
            # harness axis on a multi-axis mesh)
            n = lax.axis_size(axis)

        g_shards: Dict[str, Any] = {}
        p_shards: Optional[Dict[str, Any]] = {} if p_leaves is not None else None
        for key in sorted(groups):
            idxs = groups[key]
            pad = state.pads[key]
            flat_g = _flatten_group(leaves, idxs, pad)
            if axis is not None and n > 1:
                # Reduce-scatter: this rank receives the cross-rank SUM of
                # its 1/n slice (the first half of a ring allreduce). The
                # wire is compressed; the shard is decompressed locally.
                wire, cctx = compression.compress(flat_g)
                g_shard = lax.psum_scatter(
                    wire, axis, scatter_dimension=0, tiled=True
                )
                g_shard = compression.decompress(g_shard, cctx)
            else:
                g_shard = flat_g
            if average and n > 1:
                g_shard = g_shard / n
            g_shards[key] = g_shard
            if p_leaves is not None:
                flat_p = _flatten_group(p_leaves, idxs, pad)
                if axis is not None and n > 1:
                    shard = pad // n
                    idx = lax.axis_index(axis)
                    flat_p = lax.dynamic_slice_in_dim(
                        flat_p, idx * shard, shard
                    )
                p_shards[key] = flat_p

        upd_shards, new_inner = optimizer.update(
            g_shards, state.inner, p_shards
        )

        out: list = [None] * len(leaves)
        for key in sorted(groups):
            idxs = groups[key]
            upd = upd_shards[key]
            if axis is not None and n > 1:
                # All-gather the updated slice (the second half of the
                # ring); every rank reconstructs the full update vector.
                upd = lax.all_gather(upd, axis, tiled=True)
            _split_group(upd, leaves, idxs, out)
        new_updates = jax.tree_util.tree_unflatten(
            treedef,
            [o.astype(l.dtype) for o, l in zip(out, leaves)],
        )
        return new_updates, ZeroState(new_inner, state.pads)

    return optax.GradientTransformation(init_fn, update_fn)


def state_partition_specs(opt_state, axis_name: Optional[str] = None):
    """Partition specs for a (possibly nested) optimizer state containing
    :class:`ZeroState` nodes: the flat sharded vectors get ``P(axis)``,
    everything else (scalar counts, non-ZeRO states) stays replicated.

    ``axis_name=None`` resolves the data axis through the bound
    :class:`~horovod_tpu.parallel.logical.LogicalMesh` rules table
    (legacy ``"hvd"`` when none is bound).

    Use for both ``in_specs`` and ``out_specs`` of the training step::

        spec = TrainState(params=P(), batch_stats=P(), step=P(),
                          opt_state=zero.state_partition_specs(opt_state))
    """
    axis_name = module_axis("data", axis_name)

    def spec_for(node):
        if isinstance(node, ZeroState):
            pads = set(node.pads.values())
            inner = jax.tree_util.tree_map(
                lambda l: (
                    P(axis_name)
                    if getattr(l, "ndim", None) == 1 and l.shape[0] in pads
                    else P()
                ),
                node.inner,
            )
            return ZeroState(inner, node.pads)
        return P()

    return jax.tree_util.tree_map(
        spec_for, opt_state, is_leaf=lambda n: isinstance(n, ZeroState)
    )


def shard_info(opt_state) -> Dict[str, Tuple[int, int]]:
    """{dtype_key: (global_padded_len, per_rank_len)} for every ZeroState
    found in ``opt_state`` (merged); introspection/testing helper."""
    n = basics.size()
    info: Dict[str, Tuple[int, int]] = {}

    def visit(node):
        if isinstance(node, ZeroState):
            for key, pad in node.pads.items():
                info[key] = (pad, pad // n)
        return node

    jax.tree_util.tree_map(
        visit, opt_state, is_leaf=lambda x: isinstance(x, ZeroState)
    )
    return info
