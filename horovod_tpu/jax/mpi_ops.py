"""Collective operations: allreduce / allgather / broadcast / alltoall.

This is the binding layer the reference implemented three times over
(horovod/tensorflow/mpi_ops.py, horovod/torch/mpi_ops.py,
horovod/mxnet/mpi_ops.py) on top of EnqueueTensorAllreduce/Allgather/
Broadcast (horovod/common/operations.h:76-126). The TPU-native rebuild has
two execution paths:

* **SPMD path** (inside :func:`horovod_tpu.parallel.spmd.spmd_run` or any
  region with the "hvd" mesh axis active): ops lower directly to
  ``jax.lax`` collectives on the ICI. No negotiation — replicas execute one
  compiled program, so readiness coordination (reference operations.cc:
  2030-2380) is a non-problem by construction.

* **Eager path** (concrete arrays outside any SPMD region): process-level
  collectives. With one process this degenerates to the reference's
  ``size()==1`` behavior (identity results); with multiple processes the
  arrays travel over the JAX distributed runtime (ICI/DCN), or over the
  native CPU core when running without accelerators.

Gradients: the reference registered custom gradients (allreduce grad =
allreduce, allgather grad = allreduce+slice, broadcast grad = allreduce
zeroed off-root; horovod/tensorflow/mpi_ops.py:94-183). Here they come for
free: ``lax.psum``/``all_gather``/``all_to_all`` are differentiable and
their transposes are exactly those rules.

Async API: JAX dispatch is asynchronous by nature, so ``*_async`` returns a
:class:`Handle` immediately; ``synchronize`` blocks on device completion;
``poll`` is non-blocking readiness (reference handle manager,
horovod/torch/handle_manager.h:31-42).
"""

from __future__ import annotations

import re
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.exceptions import (
    InvalidArgumentError,
    PreconditionError,
)
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.jax.compression import Compression

# --------------------------------------------------------------------------
# Reduction ops (superset of the reference's average flag).


class Sum:
    pass


class Average:
    pass


class Min:
    pass


class Max:
    pass


class Product:
    pass


def _axis_size(axis) -> int:
    """Static size of the active SPMD axis (works for sub-meshes, where the
    global device count would be wrong)."""
    return lax.axis_size(axis)


def _pprod(tensor, axis):
    """Cross-rank elementwise product. XLA has no product collective;
    gather + local product keeps it exact (log/exp would lose signs)."""
    gathered = lax.all_gather(tensor, axis)
    return jnp.prod(gathered, axis=0)


_REDUCE_FNS = {
    Sum: lax.psum,
    Average: lax.pmean,
    Min: lax.pmin,
    Max: lax.pmax,
    Product: _pprod,
}


# --------------------------------------------------------------------------
# Naming + handle machinery.

_name_regex = re.compile(r"[^a-zA-Z0-9_.]")
_auto_name_lock = threading.Lock()
_auto_name_counter = 0
# In-flight eager async op names; the reference rejected duplicate in-flight
# names during negotiation (operations.cc:2497-2506).
_in_flight: set = set()
_in_flight_lock = threading.Lock()


def _normalize_name(name: str) -> str:
    """Mirror the reference's op-name normalization
    (horovod/tensorflow/mpi_ops.py:73-91)."""
    return _name_regex.sub("_", name)


def _auto_name(op: str, tensor) -> str:
    global _auto_name_counter
    with _auto_name_lock:
        _auto_name_counter += 1
        return f"{op}.noname.{_auto_name_counter}"


class Handle:
    """Async-op handle (reference handle_manager.h:31-42).

    Deterministic cleanup: :meth:`release` frees the op's in-flight name
    immediately (idempotent; implied by :meth:`wait`/:meth:`poll`-done),
    and the handle is a context manager whose exit releases. ``__del__``
    stays only as a GC backstop — relying on it alone left a dropped
    handle's name poisoned until collection (VERDICT round-5 weak #6).
    """

    __slots__ = ("_value", "_name", "_done_cb", "__weakref__")

    def __init__(self, value, name: str, done_cb=None):
        self._value = value
        self._name = name
        self._done_cb = done_cb

    def __del__(self):
        # Backstop only: a dropped handle must not poison its name forever.
        try:
            self.release()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    @property
    def name(self) -> str:
        return self._name

    def poll(self) -> bool:
        try:
            ready = bool(self._value.is_ready())
        except AttributeError:
            ready = True
        if ready:
            self.release()
        return ready

    def wait(self):
        jax.block_until_ready(self._value)
        self.release()
        return self._value

    def release(self) -> None:
        """Free the op's in-flight name without waiting on the value.

        The eager-path value is already dispatched (JAX owns its
        lifetime); the only resource a Handle holds is the duplicate-
        name-detection registration, which this drops deterministically.
        """
        if self._done_cb is not None:
            cb, self._done_cb = self._done_cb, None
            cb()


def poll(handle: Handle) -> bool:
    """Non-blocking readiness check (reference torch/mpi_ops.py:406-416)."""
    return handle.poll()


def synchronize(handle: Handle):
    """Block until the async op completes and return its result
    (reference torch/mpi_ops.py:422-438)."""
    return handle.wait()


def _register_in_flight(name: str):
    with _in_flight_lock:
        if name in _in_flight:
            raise PreconditionError(
                f"Duplicate in-flight tensor name {name!r}: a collective with "
                "this name has been submitted and not yet completed "
                "(reference operations.cc:2497-2506)."
            )
        _in_flight.add(name)


def _release_in_flight(name: str):
    def _done():
        with _in_flight_lock:
            _in_flight.discard(name)

    return _done


# --------------------------------------------------------------------------
# Helpers shared by the collectives.


def _spmd_axis_or_none():
    return current_spmd_axis()


def _eager_world():
    """(process_count, process_index) for the eager path."""
    st = global_state()
    st.require_init()
    return st.process_count, st.process_index


def _timeline():
    return global_state().timeline


# --------------------------------------------------------------------------
# Allreduce.


def allreduce(
    tensor,
    average: bool = True,
    name: Optional[str] = None,
    compression=Compression.none,
    op=None,
):
    """Sum (or average) ``tensor`` across all ranks.

    SPMD path: ``lax.psum``/``pmean`` over the "hvd" axis — XLA lowers this
    to an ICI ring/tree all-reduce (the hand-written ring in reference
    operations.cc:1437-1446 is the compiler's job here).

    ``op`` overrides ``average`` when given (Sum/Average/Min/Max).
    """
    global_state().require_init()
    if op is None:
        op = Average if average else Sum
    if op not in _REDUCE_FNS:
        raise InvalidArgumentError(f"Unsupported reduction op: {op}")
    axis = _spmd_axis_or_none()
    name = _normalize_name(name) if name else _auto_name("allreduce", tensor)

    tensor = jnp.asarray(tensor)
    if axis is not None:
        compressed, ctx = compression.compress(tensor)
        if op is Average:
            # Sum in wire dtype, average in accumulation dtype: matches the
            # reference order (allreduce then divide,
            # horovod/torch/mpi_ops_v2.cc:66-72) and avoids fp16 overflow
            # from dividing after upcast.
            summed = lax.psum(compressed, axis)
            out = compression.decompress(summed, ctx)
            return out / _axis_size(axis)
        summed = _REDUCE_FNS[op](compressed, axis)
        return compression.decompress(summed, ctx)

    # Eager process-level path.
    nproc, _ = _eager_world()
    tl = _timeline()
    if tl is not None:
        tl.start(name, "ALLREDUCE")
    try:
        if nproc == 1:
            # size()==1 semantics: sum == value == average == min == max.
            return tensor
        from horovod_tpu.jax import eager as _eager

        if op in (Min, Max, Product):
            gathered = _eager.process_allgather(tensor[None])
            reduce = {Min: jnp.min, Max: jnp.max, Product: jnp.prod}[op]
            return reduce(gathered.reshape((nproc,) + tensor.shape), axis=0)
        compressed, ctx = compression.compress(tensor)
        summed = _eager.process_allreduce(compressed)
        out = compression.decompress(summed, ctx)
        if op is Average:
            out = out / nproc
        return out
    finally:
        if tl is not None:
            tl.end(name, "ALLREDUCE")


def allreduce_async(tensor, average=True, name=None, compression=Compression.none, op=None):
    name = _normalize_name(name) if name else _auto_name("allreduce", tensor)
    _register_in_flight(name)
    try:
        value = allreduce(tensor, average=average, name=name, compression=compression, op=op)
    except Exception:
        _release_in_flight(name)()
        raise
    return Handle(value, name, _release_in_flight(name))


# JAX arrays are immutable; the in-place variants exist for API parity with
# the reference (torch/mpi_ops.py:180-230) and return the new array.
def allreduce_(tensor, average=True, name=None, compression=Compression.none, op=None):
    return allreduce(tensor, average=average, name=name, compression=compression, op=op)


def allreduce_async_(tensor, average=True, name=None, compression=Compression.none, op=None):
    return allreduce_async(tensor, average=average, name=name, compression=compression, op=op)


# --------------------------------------------------------------------------
# Grouped allreduce (fusion surface).


def grouped_allreduce(
    tensors,
    average: bool = True,
    name: Optional[str] = None,
    compression=Compression.none,
    op=None,
    fusion_threshold: Optional[int] = None,
    overlap: Optional[str] = None,
    hierarchical: Optional[str] = None,
):
    """Allreduce a list of tensors as fused flat buckets.

    TPU-native equivalent of the reference's tensor fusion (operations.cc:
    2160-2264 + fusion_buffer_manager): tensors are grouped by dtype,
    flattened and concatenated into buckets of at most the fusion threshold
    (HOROVOD_FUSION_THRESHOLD, default 64 MB), each bucket is one
    ``lax.psum``, then the results are split back out. One big ICI
    all-reduce amortizes latency exactly like the reference's fusion buffer
    amortized NCCL launch + ring latency. ``overlap`` (auto|on|off)
    selects the backward-overlapped bucket emission and ``hierarchical``
    (auto|on|off) the two-level ICI/DCN ladder — see
    :mod:`horovod_tpu.jax.fusion`.
    """
    from horovod_tpu.jax.fusion import fused_reduce

    return fused_reduce(
        tensors,
        average=average,
        compression=compression,
        op=op,
        fusion_threshold=fusion_threshold,
        overlap=overlap,
        hierarchical=hierarchical,
        name=_normalize_name(name) if name else None,
    )


# --------------------------------------------------------------------------
# Allgather.


def allgather(tensor, name: Optional[str] = None):
    """Concatenate ``tensor`` from all ranks along dimension 0.

    SPMD path: ``lax.all_gather(..., tiled=True)``. Note XLA requires equal
    shapes across ranks inside one program; the reference's ragged
    allgatherv (first dims differing per rank, operations.cc:843-925) is
    available as :func:`allgatherv` (pad+mask) and on the eager
    process-level path (true ragged).
    """
    global_state().require_init()
    axis = _spmd_axis_or_none()
    tensor = jnp.asarray(tensor)
    name = _normalize_name(name) if name else _auto_name("allgather", tensor)

    if axis is not None:
        st = global_state()
        if st.config.hierarchical_allgather:
            # HOROVOD_HIERARCHICAL_ALLGATHER: two-phase gather (reference
            # operations.cc:929-1032 — node-shared window, then cross-node
            # stripes). Inner/outer factorization as in fused_reduce.
            from horovod_tpu.jax.fusion import _hierarchical_inner
            from horovod_tpu.parallel.mesh import hierarchical_allgather_in_axis

            inner = _hierarchical_inner(st, _axis_size(axis), True)
            if inner:
                return hierarchical_allgather_in_axis(tensor, axis, inner)
        return lax.all_gather(tensor, axis, tiled=True)

    nproc, _ = _eager_world()
    tl = _timeline()
    if tl is not None:
        tl.start(name, "ALLGATHER")
    try:
        if nproc == 1:
            return tensor
        from horovod_tpu.jax import eager as _eager

        return _eager.process_allgather(tensor)
    finally:
        if tl is not None:
            tl.end(name, "ALLGATHER")


def allgather_async(tensor, name=None):
    name = _normalize_name(name) if name else _auto_name("allgather", tensor)
    _register_in_flight(name)
    try:
        value = allgather(tensor, name=name)
    except Exception:
        _release_in_flight(name)()
        raise
    return Handle(value, name, _release_in_flight(name))


def allgatherv(tensor, valid_rows, max_rows: int, name: Optional[str] = None):
    """Ragged allgather under SPMD static shapes.

    The reference negotiated per-rank first-dim sizes at runtime
    (operations.cc:855-925). In one compiled SPMD program shapes are static,
    so the TPU-native contract is: pad to ``max_rows``, gather, and return
    ``(gathered, row_counts)`` where ``row_counts[r]`` rows of block ``r``
    are valid. ``valid_rows`` may be a traced per-rank scalar.
    """
    axis = _spmd_axis_or_none()
    if axis is None:
        raise PreconditionError("allgatherv is only available inside spmd_run")
    tensor = jnp.asarray(tensor)
    pad = [(0, max_rows - tensor.shape[0])] + [(0, 0)] * (tensor.ndim - 1)
    padded = jnp.pad(tensor, pad)
    gathered = lax.all_gather(padded, axis, tiled=True)
    counts = lax.all_gather(jnp.asarray(valid_rows, jnp.int32), axis)
    return gathered, counts


# --------------------------------------------------------------------------
# Broadcast.


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Broadcast ``tensor`` from ``root_rank`` to all ranks.

    SPMD path: masked psum (value where rank==root, zeros elsewhere, then
    sum) — on ICI this compiles to a broadcast-equivalent collective. The
    reference used MPI_Bcast (operations.cc:1592-1612) and never fused
    broadcasts; we keep that (no bucketing here).
    """
    global_state().require_init()
    axis = _spmd_axis_or_none()
    tensor = jnp.asarray(tensor)
    name = _normalize_name(name) if name else _auto_name("broadcast", tensor)

    if axis is not None:
        n = _axis_size(axis)
        if not 0 <= root_rank < n:
            raise InvalidArgumentError(
                f"broadcast root_rank {root_rank} out of range for axis size {n}"
            )
        idx = lax.axis_index(axis)
        masked = jnp.where(idx == root_rank, tensor, jnp.zeros_like(tensor))
        if jnp.issubdtype(tensor.dtype, jnp.bool_):
            return lax.psum(masked.astype(jnp.int8), axis).astype(jnp.bool_)
        return lax.psum(masked, axis)

    nproc, _ = _eager_world()
    tl = _timeline()
    if tl is not None:
        tl.start(name, "BROADCAST")
    try:
        if nproc == 1:
            if root_rank != 0:
                raise InvalidArgumentError(
                    f"root_rank {root_rank} out of range for a 1-process job"
                )
            return tensor
        from horovod_tpu.jax import eager as _eager

        return _eager.process_broadcast(tensor, root_rank)
    finally:
        if tl is not None:
            tl.end(name, "BROADCAST")


def broadcast_async(tensor, root_rank, name=None):
    name = _normalize_name(name) if name else _auto_name("broadcast", tensor)
    _register_in_flight(name)
    try:
        value = broadcast(tensor, root_rank, name=name)
    except Exception:
        _release_in_flight(name)()
        raise
    return Handle(value, name, _release_in_flight(name))


def broadcast_(tensor, root_rank, name=None):
    return broadcast(tensor, root_rank, name=name)


def broadcast_async_(tensor, root_rank, name=None):
    return broadcast_async(tensor, root_rank, name=name)


# --------------------------------------------------------------------------
# Alltoall (TPU extension; the reference gained alltoall only in later
# versions, but it is load-bearing here for Ulysses-style sequence
# parallelism in horovod_tpu.parallel).


def alltoall(tensor, name: Optional[str] = None, split_axis: int = 0, concat_axis: int = 0):
    """Scatter equal splits of dim ``split_axis`` to all ranks and gather the
    received splits along ``concat_axis``.

    SPMD path: ``lax.all_to_all`` over the mesh axis. Eager multi-process
    path: the same pairwise exchange compiled over a one-device-per-process
    mesh (``eager.process_alltoall``) — O(bytes) sent and received per
    rank, MPI_Alltoall's wire shape."""
    axis = _spmd_axis_or_none()
    tensor = jnp.asarray(tensor)
    split_axis = split_axis % tensor.ndim
    concat_axis = concat_axis % tensor.ndim
    if axis is None:
        nproc, me = _eager_world()
        if nproc == 1:
            return tensor
        if tensor.shape[split_axis] % nproc != 0:
            raise InvalidArgumentError(
                f"alltoall split dim {tensor.shape[split_axis]} not "
                f"divisible by world size {nproc}")
        # Process-level eager path: a TRUE pairwise exchange compiled
        # over a one-device-per-process mesh — each rank sends and
        # receives O(bytes), not the O(n*bytes) of the old
        # allgather-then-select fallback (VERDICT r5 weak #5; the
        # reference's MPI_Alltoall had the pairwise shape all along).
        from horovod_tpu.jax import eager as _eager

        return _eager.process_alltoall(
            tensor, split_axis=split_axis, concat_axis=concat_axis)
    n = _axis_size(axis)
    if tensor.shape[split_axis] % n != 0:
        raise InvalidArgumentError(
            f"alltoall split dim {tensor.shape[split_axis]} not divisible by "
            f"world size {n}"
        )
    return lax.all_to_all(
        tensor, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


# --------------------------------------------------------------------------
# Reduce-scatter (TPU extension; building block of sharded optimizers and
# the hierarchical path).


def reducescatter(tensor, average: bool = True, name: Optional[str] = None):
    """Reduce across ranks and scatter dim-0 shards.

    SPMD path: ``lax.psum_scatter``. Eager multi-process path: the same
    ring reduce-scatter compiled over a one-device-per-process mesh
    (``eager.process_reducescatter``) — (n-1)/n of the tensor bytes per
    rank, and results identical to slicing a full reduce."""
    axis = _spmd_axis_or_none()
    if axis is None:
        nproc, me = _eager_world()
        tensor = jnp.asarray(tensor)
        if nproc == 1:
            return tensor
        if tensor.shape[0] % nproc != 0:
            raise InvalidArgumentError(
                f"reducescatter dim 0 ({tensor.shape[0]}) not divisible "
                f"by world size {nproc}")
        # Process-level eager path: a ring reduce-scatter compiled over a
        # one-device-per-process mesh — (n-1)/n of the tensor bytes per
        # rank instead of the old full-reduce-then-slice's whole-tensor
        # allreduce (VERDICT r5 weak #5); results match the sliced full
        # reduce exactly (same psum_scatter the SPMD lane lowers to).
        from horovod_tpu.jax import eager as _eager

        out = _eager.process_reducescatter(tensor)
        return out / nproc if average else out
    tensor = jnp.asarray(tensor)
    n = _axis_size(axis)
    if tensor.shape[0] % n != 0:
        raise InvalidArgumentError(
            f"reducescatter dim 0 ({tensor.shape[0]}) not divisible by world "
            f"size {n}"
        )
    out = lax.psum_scatter(tensor, axis, scatter_dimension=0, tiled=True)
    if average:
        out = out / n
    return out


# --------------------------------------------------------------------------
# Sparse allreduce (reference tensorflow/__init__.py:72-83: a sparse
# tf.IndexedSlices gradient is allreduced as allgather(values) +
# allgather(indices) — summing slice contributions without densifying the
# full embedding table on the wire).


def allreduce_sparse(indices, values, dense_rows: Optional[int] = None,
                     average: bool = True, name: Optional[str] = None):
    """Cross-rank reduction of a sparse row update set.

    ``indices`` [k] are row ids into a [dense_rows, ...] tensor; ``values``
    [k, ...] the per-row contributions. Returns:

    * with ``dense_rows``: the dense [dense_rows, ...] summed (or averaged)
      gradient — duplicate rows across ranks accumulate, exactly what
      ``sparse_as_dense`` produced in the reference
      (tensorflow/__init__.py:183-209);
    * without: ``(gathered_indices, gathered_values)``, the reference's raw
      IndexedSlices semantics (duplicates left to the consumer).
    """
    global_state().require_init()
    axis = _spmd_axis_or_none()
    name = _normalize_name(name) if name else _auto_name("sparse", values)
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    if axis is not None:
        all_indices = lax.all_gather(indices, axis, axis=0, tiled=True)
        all_values = lax.all_gather(values, axis, axis=0, tiled=True)
        n = _axis_size(axis)
    else:
        nproc, _ = _eager_world()
        tl = _timeline()
        if tl is not None:
            tl.start(name, "SPARSE_ALLREDUCE")
        try:
            if nproc == 1:
                all_indices, all_values, n = indices, values, 1
            else:
                from horovod_tpu.jax import eager

                all_indices = eager.process_allgather(indices)
                all_values = eager.process_allgather(values)
                n = nproc
        finally:
            if tl is not None:
                tl.end(name)
    if average:
        all_values = all_values / n
    if dense_rows is None:
        return all_indices, all_values
    dense = jnp.zeros((dense_rows,) + all_values.shape[1:],
                      all_values.dtype)
    return dense.at[all_indices].add(all_values)
