"""DistributedOptimizer and parameter/optimizer-state broadcast.

Parity targets:

* ``DistributedOptimizer`` — reference horovod/torch/__init__.py:42-197 and
  horovod/tensorflow/__init__.py:151-249: wrap a user optimizer so gradients
  are averaged across ranks before the update, with optional compression and
  ``backward_passes_per_step`` local accumulation.
* ``broadcast_parameters`` — reference torch/__init__.py:200-229.
* ``broadcast_optimizer_state`` — reference torch/__init__.py:232-348. The
  reference needed elaborate scalar->tensor wrapping because torch optimizer
  state mixes Python scalars and tensors; optax states are pytrees of
  arrays, so a pytree broadcast subsumes it.

TPU-native design: the optimizer is an ``optax.GradientTransformation``
wrapper whose update step fuses gradient leaves into flat buckets
(:mod:`horovod_tpu.jax.fusion`) and reduces each with one ``lax.psum``. The
reference fired one allreduce per gradient from a backward hook as autograd
produced them (torch/__init__.py:95-130), relying on the background fusion
thread to batch them; under XLA the whole step is one program, so bucketing
at trace time achieves the same overlap with zero runtime coordination.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.common import basics
from horovod_tpu.common.state import current_spmd_axis, global_state
from horovod_tpu.jax import mpi_ops
from horovod_tpu.jax.compression import Compression, is_dcn_wire
from horovod_tpu.jax.fusion import (
    ef_residual_specs,
    fused_reduce,
    resolve_hierarchical,
)


class _AllreduceState(NamedTuple):
    """State of the allreduce transform. ``residuals`` is empty except
    under a low-bit DCN wire codec (Compression.int8/fp8) on an engaged
    hierarchical ladder, where it carries the error-feedback residual
    vectors (:func:`horovod_tpu.jax.fusion.ef_residual_specs`) — GLOBAL
    shapes at init, rank-local slices inside the SPMD region. These
    leaves are rank-VARYING state: feed the train state through
    ``models.state_partition_specs`` (or map them to ``P("hvd")``
    yourself) so each chip keeps its own slice across steps."""

    residuals: tuple = ()


def allreduce_gradients_transform(
    compression=Compression.none,
    op=None,
    average: bool = True,
    fusion_threshold: Optional[int] = None,
    overlap: Optional[str] = None,
    hierarchical: Optional[str] = None,
) -> optax.GradientTransformation:
    """An optax transform that replaces gradients with their cross-rank
    (fused) allreduce. Composable with any optax chain.

    ``overlap`` (auto|on|off; default HOROVOD_OVERLAP) selects the
    backward-overlapped bucket emission (:mod:`horovod_tpu.jax.fusion`):
    per-bucket collectives issued in reverse bucket order as each
    bucket's gradients become available, so XLA's async collective
    scheduling hides them under remaining backward compute. Dispatch
    shape only — numerics are bit-identical across modes.

    ``hierarchical`` (auto|on|off; default HOROVOD_HIERARCHICAL) runs
    each bucket as the intra-slice reduce-scatter -> inter-slice (DCN)
    exchange -> intra-slice all-gather ladder; with
    ``Compression.int8``/``.fp8`` the DCN leg is absmax-quantized and
    the quantization error carried forward as an error-feedback
    residual in this transform's state (re-injected next step, the
    1-bit-SGD/DGC discipline).
    """

    def _ef_engaged():
        if not is_dcn_wire(compression):
            return 0
        return resolve_hierarchical(hierarchical, basics.size())

    def init_fn(params):
        inner = _ef_engaged()
        if not inner:
            return _AllreduceState()
        st = global_state()
        threshold = (fusion_threshold if fusion_threshold is not None
                     else st.config.fusion_threshold)
        leaves = jax.tree_util.tree_leaves(params)
        specs = ef_residual_specs(leaves, threshold, basics.size(), inner)
        return _AllreduceState(residuals=tuple(
            jnp.zeros(s.shape, s.dtype) for s in specs))

    def update_fn(updates, state, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        kwargs = dict(
            average=average,
            compression=compression,
            op=op,
            fusion_threshold=fusion_threshold,
            overlap=overlap,
            hierarchical=hierarchical,
            name="grads",
        )
        if state.residuals:
            reduced, new_res = fused_reduce(
                leaves, residuals=state.residuals, **kwargs)
            state = _AllreduceState(residuals=new_res)
        else:
            reduced = fused_reduce(leaves, **kwargs)
        return jax.tree_util.tree_unflatten(treedef, reduced), state

    return optax.GradientTransformation(init_fn, update_fn)


def ef_state_partition_specs(opt_state, axis_name: Optional[str] = None):
    """Partition specs for an optimizer state that may contain
    :class:`_AllreduceState` error-feedback residuals: residual vectors
    get ``P(axis)`` (rank-local shards), everything else replicated.
    ``axis_name=None`` resolves the data axis through the bound
    :class:`~horovod_tpu.parallel.logical.LogicalMesh` rules table
    (legacy ``"hvd"`` when none is bound).
    ``models.state_partition_specs`` composes this with the ZeRO spec
    derivation; use directly when hand-building specs."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.logical import module_axis

    axis_name = module_axis("data", axis_name)

    def spec_for(node):
        if isinstance(node, _AllreduceState):
            return _AllreduceState(residuals=tuple(
                P(axis_name) for _ in node.residuals))
        return P()

    return jax.tree_util.tree_map(
        spec_for, opt_state,
        is_leaf=lambda n: isinstance(n, _AllreduceState))


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters=None,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    op=None,
    average: bool = True,
    fusion_threshold: Optional[int] = None,
    overlap: Optional[str] = None,
    hierarchical: Optional[str] = None,
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates see cross-rank-averaged gradients.

    ``named_parameters`` is accepted for signature parity with the reference
    (torch/__init__.py:42-68, where it keyed per-tensor allreduce names);
    bucket fusion makes per-tensor names unnecessary, so it is ignored.

    ``backward_passes_per_step > 1`` accumulates gradients locally for k
    calls and performs the (single) fused allreduce + update on the k-th,
    reproducing the reference's delayed-allreduce accumulation
    (torch/__init__.py:71-73,114-130).

    ``overlap`` (auto|on|off) selects the backward-overlapped bucket
    schedule and ``hierarchical`` (auto|on|off) the two-level
    ICI/DCN ladder (with error-feedback residuals in this optimizer's
    state under ``Compression.int8``/``.fp8``) — see
    :func:`allreduce_gradients_transform`.
    """
    del named_parameters
    chain = optax.chain(
        allreduce_gradients_transform(
            compression=compression,
            op=op,
            average=average,
            fusion_threshold=fusion_threshold,
            overlap=overlap,
            hierarchical=hierarchical,
        ),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(
            chain, every_k_schedule=backward_passes_per_step
        ).gradient_transformation()
    return chain


def grad(loss_fn, argnums=0, has_aux: bool = False):
    """``jax.grad`` + cross-rank gradient averaging.

    Functional analogue of the reference's ``DistributedGradientTape``
    (tensorflow/__init__.py:252-326): differentiates ``loss_fn`` and fuses +
    allreduces the gradients before returning them.
    """
    gfn = jax.grad(loss_fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        out = gfn(*args, **kwargs)
        grads, aux = (out[0], out[1]) if has_aux else (out, None)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        reduced = fused_reduce(leaves, average=True, name="grads")
        grads = jax.tree_util.tree_unflatten(treedef, reduced)
        return (grads, aux) if has_aux else grads

    return wrapped


def value_and_grad(loss_fn, argnums=0, has_aux: bool = False):
    """``jax.value_and_grad`` with cross-rank-averaged gradients and loss."""
    vgfn = jax.value_and_grad(loss_fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vgfn(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        reduced = fused_reduce(leaves, average=True, name="grads")
        grads = jax.tree_util.tree_unflatten(treedef, reduced)
        if current_spmd_axis() is not None:
            if has_aux:
                value = (mpi_ops.allreduce(value[0]), value[1])
            else:
                value = mpi_ops.allreduce(value)
        return value, grads

    return wrapped


def broadcast_parameters(params, root_rank: int = 0):
    """Replicate a parameter pytree from ``root_rank`` to all ranks
    (reference torch/__init__.py:200-229). Returns the broadcast pytree
    (arrays are immutable; assignment replaces the reference's in-place
    copy)."""
    global_state().require_init()
    return jax.tree_util.tree_map(
        lambda t: mpi_ops.broadcast(t, root_rank), params
    )


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Replicate optimizer state from ``root_rank``
    (reference torch/__init__.py:232-348)."""
    return broadcast_parameters(opt_state, root_rank)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """Broadcast an arbitrary picklable Python object from ``root_rank``.

    Process-level only (objects live on hosts, not chips). Mirrors the
    resume-epoch broadcast pattern from the reference's
    examples/keras_imagenet_resnet50.py:66-103.
    """
    st = global_state()
    st.require_init()
    if st.process_count == 1:
        return obj
    import pickle

    import numpy as np

    from horovod_tpu.jax import eager

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    length = eager.process_broadcast(
        jnp.asarray([payload.size], jnp.int32), root_rank
    )
    buf = np.zeros(int(length[0]), dtype=np.uint8)
    if st.process_index == root_rank:
        buf[:] = payload
    out = eager.process_broadcast(jnp.asarray(buf), root_rank)
    return pickle.loads(np.asarray(out).tobytes())
