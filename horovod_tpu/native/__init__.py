"""Native core loader: builds (once, cached) and binds csrc/ via ctypes.

The reference shipped prebuilt framework extensions loaded with
``ctypes.CDLL(..., RTLD_GLOBAL)`` (horovod/common/__init__.py:51-57) and a
``check_extension`` guard. This rebuild compiles the core on first use with
the host toolchain — there is no MPI/CUDA discovery to do (setup.py:294-495
in the reference), so the whole build is one g++ invocation, content-hashed
so repeat imports are free.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_CSRC = _REPO_ROOT / "csrc"
_CACHE_DIR = Path(__file__).resolve().parent / "_cache"

_SOURCES = [
    "logging.cc",
    "auth.cc",
    "message.cc",
    "transport.cc",
    "collectives.cc",
    "timeline.cc",
    "coordinator.cc",
    "autotune/gaussian_process.cc",
    "autotune/bayesian_optimization.cc",
    "autotune/parameter_manager.cc",
    "c_api.cc",
]
_HEADERS = [
    "common.h",
    "logging.h",
    "auth.h",
    "message.h",
    "transport.h",
    "collectives.h",
    "half.h",
    "timeline.h",
    "coordinator.h",
    "autotune/gaussian_process.h",
    "autotune/bayesian_optimization.h",
    "autotune/parameter_manager.h",
]

# numpy dtype -> wire id (csrc/common.h DataType).
_DTYPE_IDS = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.float16): 6,
    np.dtype(np.float32): 7,
    np.dtype(np.float64): 8,
    np.dtype(np.bool_): 9,
}
try:  # bfloat16 rides its ml_dtypes registration
    import ml_dtypes

    _DTYPE_IDS[np.dtype(ml_dtypes.bfloat16)] = 10
except ImportError:  # pragma: no cover
    pass

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

# HVD_SANITIZE=thread|address: rebuild the native core under
# TSAN/ASAN. Sanitized artifacts live under distinct cache names
# (-tsan/-asan suffix), so sanitized and plain builds coexist and
# switching the env var never serves a stale flavor. Sanitizers want
# frame pointers and modest optimization for usable reports.
_SANITIZERS = {
    "thread": ("tsan", ["-fsanitize=thread"]),
    "address": ("asan", ["-fsanitize=address"]),
}


class NativeBuildError(RuntimeError):
    pass


def sanitize_mode() -> str:
    """'' | 'thread' | 'address' from HVD_SANITIZE (invalid -> error)."""
    mode = os.environ.get("HVD_SANITIZE", "").strip().lower()
    if mode in ("", "0", "none", "off", "false"):
        return ""
    if mode not in _SANITIZERS:
        raise NativeBuildError(
            f"HVD_SANITIZE={mode!r}: expected 'thread' or 'address'")
    return mode


def _source_hash() -> str:
    h = hashlib.sha256()
    for rel in _SOURCES + _HEADERS:
        h.update((_CSRC / rel).read_bytes())
    return h.hexdigest()[:16]


def _compile(sources, out_name: str, extra_flags, shared: bool,
             force: bool) -> Path:
    """One g++ invocation into the content-hashed cache (atomic publish)."""
    _CACHE_DIR.mkdir(parents=True, exist_ok=True)
    out = _CACHE_DIR / out_name
    if out.exists() and not force:
        return out
    # Per-process temp name: N freshly-launched workers may race to build
    # the same cold cache; os.replace makes the winner atomic.
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [
        os.environ.get("CXX", "g++"),
        *extra_flags,
        "-std=c++17",
        "-fPIC",
        *(["-shared"] if shared else []),
        "-pthread",
        *(str(_CSRC / s) for s in sources),
        "-I",
        str(_CSRC),
        "-o",
        tmp,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed ({out_name}):\n{proc.stderr[-4000:]}"
        )
    os.replace(tmp, out)
    return out


def _mode_suffix_flags(mode: str):
    if not mode:
        return "", ["-O3"]
    tag, san_flags = _SANITIZERS[mode]
    return f"-{tag}", [*san_flags, "-O1", "-g", "-fno-omit-frame-pointer"]


def build_library(force: bool = False) -> Path:
    """Compile csrc/ into a cached shared library; returns its path.

    Honors HVD_SANITIZE (see sanitize_mode). Note that dlopen-ing a
    TSAN/ASAN .so into an uninstrumented interpreter needs the sanitizer
    runtime preloaded (LD_PRELOAD=libtsan.so/libasan.so); the fully
    supported sanitizer lane is the standalone stress binary
    (build_stress_binary), which instruments main() too.
    """
    suffix, flags = _mode_suffix_flags(sanitize_mode())
    return _compile(_SOURCES, f"libhvdtpu-{_source_hash()}{suffix}.so",
                    flags, shared=True, force=force)


def build_stress_binary(force: bool = False) -> Path:
    """Compile the coordinator stress test (csrc/stress_test.cc) as a
    standalone executable — the TSAN/ASAN lane's entry point, since a
    whole-program build is the only configuration the sanitizers fully
    support. Honors HVD_SANITIZE for the sanitizer choice."""
    h = hashlib.sha256(_source_hash().encode())
    h.update((_CSRC / "stress_test.cc").read_bytes())
    suffix, flags = _mode_suffix_flags(sanitize_mode())
    return _compile(_SOURCES + ["stress_test.cc"],
                    f"hvdstress-{h.hexdigest()[:16]}{suffix}",
                    flags, shared=False, force=force)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    i64p = c.POINTER(c.c_int64)
    lib.hvdtpu_init.argtypes = [c.c_int, c.c_int, c.c_int, c.c_int,
                                c.c_char_p, c.c_int, c.c_int]
    lib.hvdtpu_init.restype = c.c_int
    lib.hvdtpu_init_comm.argtypes = [c.c_int, c.c_int, c.POINTER(c.c_int),
                                     c.c_int, c.c_char_p, c.c_int, c.c_int]
    lib.hvdtpu_init_comm.restype = c.c_int
    lib.hvdtpu_shutdown.restype = None
    lib.hvdtpu_initialized.restype = c.c_int
    lib.hvdtpu_rank.restype = c.c_int
    lib.hvdtpu_size.restype = c.c_int
    lib.hvdtpu_local_rank.restype = c.c_int
    lib.hvdtpu_local_size.restype = c.c_int
    lib.hvdtpu_hierarchical_active.restype = c.c_int
    for op in ("allreduce", "allgather"):
        fn = getattr(lib, f"hvdtpu_enqueue_{op}")
        fn.argtypes = [c.c_char_p, c.c_void_p, c.c_int, c.c_int, i64p]
        fn.restype = c.c_int
    lib.hvdtpu_enqueue_broadcast.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.c_int, i64p, c.c_int]
    lib.hvdtpu_enqueue_broadcast.restype = c.c_int
    lib.hvdtpu_poll.argtypes = [c.c_int]
    lib.hvdtpu_poll.restype = c.c_int
    lib.hvdtpu_wait.argtypes = [c.c_int]
    lib.hvdtpu_wait.restype = c.c_int
    lib.hvdtpu_error.argtypes = [c.c_int, c.c_char_p, c.c_int]
    lib.hvdtpu_error.restype = c.c_int
    lib.hvdtpu_result_size.argtypes = [c.c_int]
    lib.hvdtpu_result_size.restype = c.c_int64
    lib.hvdtpu_result_copy.argtypes = [c.c_int, c.c_void_p]
    lib.hvdtpu_result_copy.restype = c.c_int
    lib.hvdtpu_release.argtypes = [c.c_int]
    lib.hvdtpu_release.restype = None
    lib.hvdtpu_set_fusion_threshold.argtypes = [c.c_int64]
    lib.hvdtpu_set_fusion_threshold.restype = None
    lib.hvdtpu_fusion_threshold.restype = c.c_int64
    lib.hvdtpu_set_cycle_time_ms.argtypes = [c.c_double]
    lib.hvdtpu_set_cycle_time_ms.restype = None
    lib.hvdtpu_cycle_time_ms.restype = c.c_double
    lib.hvdtpu_timeline_start.argtypes = [c.c_char_p, c.c_int]
    lib.hvdtpu_timeline_start.restype = c.c_int
    lib.hvdtpu_timeline_end.restype = None
    lib.hvdtpu_enable_autotune.argtypes = [c.c_char_p]
    lib.hvdtpu_enable_autotune.restype = None
    lib.hvdtpu_gp_selftest.restype = c.c_int
    dp = c.POINTER(c.c_double)
    lib.hvdtpu_ei_next.argtypes = [dp, dp, c.c_int, dp, c.c_int, c.c_double]
    lib.hvdtpu_ei_next.restype = c.c_int
    lib.hvdtpu_pm_create.argtypes = [c.c_int]
    lib.hvdtpu_pm_create.restype = c.c_void_p
    lib.hvdtpu_pm_feed.argtypes = [
        c.c_void_p, c.c_double, c.POINTER(c.c_double),
        c.POINTER(c.c_longlong), c.POINTER(c.c_int)]
    lib.hvdtpu_pm_feed.restype = c.c_int
    lib.hvdtpu_pm_destroy.argtypes = [c.c_void_p]
    lib.hvdtpu_pm_destroy.restype = None
    return lib


def ei_next(xs, ys, candidates, xi: float = 0.01) -> int:
    """Index of the candidate maximizing expected improvement given the
    (position, score) observations — the native GP/EI machinery
    (csrc/autotune/) serving any Python-side sweep. Returns -1 when the
    GP cannot be fit (caller falls back to sequential order)."""
    import ctypes as c

    lib = load_library()
    n, m = len(xs), len(candidates)
    ax = (c.c_double * n)(*[float(v) for v in xs])
    ay = (c.c_double * n)(*[float(v) for v in ys])
    ac = (c.c_double * m)(*[float(v) for v in candidates])
    return int(lib.hvdtpu_ei_next(ax, ay, n, ac, m, float(xi)))


def load_library() -> ctypes.CDLL:
    global _lib
    with _build_lock:
        if _lib is None:
            path = build_library()
            try:
                # RTLD_GLOBAL mirrors the reference loader
                # (horovod/common/__init__.py:55).
                _lib = _bind(ctypes.CDLL(str(path), mode=ctypes.RTLD_GLOBAL))
            except OSError as e:
                mode = sanitize_mode()
                if mode:
                    rt = "libtsan.so.0" if mode == "thread" else "libasan.so.6"
                    raise NativeBuildError(
                        f"could not dlopen the HVD_SANITIZE={mode} build "
                        f"({e}). Sanitizer runtimes must be loaded before "
                        f"the interpreter: re-run under LD_PRELOAD={rt}, or "
                        "use the fully-instrumented stress binary lane "
                        "(horovod_tpu.native.build_stress_binary / "
                        "tools/check.sh --sanitize) instead."
                    ) from e
                raise
    return _lib


class StatusCode:
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


class NativeError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(message or f"native core error (code {code})")
        self.code = code


class NativeCore:
    """High-level handle API over the C core (numpy in/out)."""

    def __init__(self):
        self.lib = load_library()
        # Keeps enqueued arrays alive until release: the background thread
        # writes through raw pointers (mirrors reference _handle_map,
        # torch/mpi_ops.py:51-54).
        self._live: dict = {}
        self._names: dict = {}
        self._live_lock = threading.Lock()
        # Bounded completion deadline (HOROVOD_NEGOTIATION_TIMEOUT secs;
        # 0 = reference behavior, wait forever). A stalled negotiation —
        # a peer died mid-run, or rank-divergent control flow skipped a
        # collective — then raises a typed HorovodTimeoutError instead
        # of hanging silently; the elastic supervisor turns that into a
        # relaunch from the last snapshot (horovod_tpu/elastic/).
        try:
            self._default_timeout = float(
                os.environ.get("HOROVOD_NEGOTIATION_TIMEOUT", "0") or "0")
        except ValueError:
            self._default_timeout = 0.0

    # -- lifecycle ---------------------------------------------------------
    def init(self, rank: int = 0, size: int = 1, local_rank: int = 0,
             local_size: int = 1, coord_host: str = "127.0.0.1",
             coord_port: int = 0, timeout_ms: int = 60000,
             comm=None) -> None:
        """``comm`` restricts this process to a sub-communicator of the
        launched world (reference hvd.init(comm=[ranks]),
        common/__init__.py:58-84). Collective like MPI_Comm_split: every
        launched process must call init; after success rank()/size()
        report sub-world values (rank = position in comm) and local_*
        are regrouped by members' self-IPs."""
        if comm is not None and list(comm) == list(range(size)):
            comm = None  # full world: keep the launcher's local grouping
        if comm is not None:
            members = [int(r) for r in comm]
            arr = (ctypes.c_int * len(members))(*members)
            rc = self.lib.hvdtpu_init_comm(rank, size, arr, len(members),
                                           coord_host.encode(), coord_port,
                                           timeout_ms)
        else:
            rc = self.lib.hvdtpu_init(rank, size, local_rank, local_size,
                                      coord_host.encode(), coord_port,
                                      timeout_ms)
        if rc != 0:
            raise NativeError(rc, self._error(-1))

    def shutdown(self) -> None:
        self.lib.hvdtpu_shutdown()

    @property
    def initialized(self) -> bool:
        return bool(self.lib.hvdtpu_initialized())

    def rank(self) -> int:
        return self.lib.hvdtpu_rank()

    def size(self) -> int:
        return self.lib.hvdtpu_size()

    def local_rank(self) -> int:
        return self.lib.hvdtpu_local_rank()

    def local_size(self) -> int:
        return self.lib.hvdtpu_local_size()

    def hierarchical_active(self) -> int:
        """Bitmask of active two-level collective paths: 1 = allreduce,
        2 = allgather (0 when the flat ring is in use)."""
        return self.lib.hvdtpu_hierarchical_active()

    # -- enqueue -----------------------------------------------------------
    def _dtype_id(self, arr: np.ndarray) -> int:
        try:
            return _DTYPE_IDS[arr.dtype]
        except KeyError:
            raise TypeError(f"unsupported dtype {arr.dtype}") from None

    def _dims(self, arr: np.ndarray):
        return (ctypes.c_int64 * arr.ndim)(*arr.shape) if arr.ndim else \
            (ctypes.c_int64 * 0)()

    def _track(self, handle: int, arr: np.ndarray,
               name: str = "") -> int:
        if handle < 0:
            raise NativeError(StatusCode.INVALID_ARGUMENT, self._error(-1))
        with self._live_lock:
            self._live[handle] = arr
            if name:
                self._names[handle] = name
        return handle

    def allreduce_async_(self, name: str, arr: np.ndarray) -> int:
        """In-place async allreduce; the core writes through the raw
        pointer, so the array is pinned in self._live until release."""
        assert arr.flags["C_CONTIGUOUS"] and arr.flags["WRITEABLE"]
        return self._track(self.lib.hvdtpu_enqueue_allreduce(
            name.encode(), arr.ctypes.data, self._dtype_id(arr), arr.ndim,
            self._dims(arr)), arr, name)

    def allgather_async(self, name: str, arr: np.ndarray) -> int:
        assert arr.flags["C_CONTIGUOUS"]
        return self._track(self.lib.hvdtpu_enqueue_allgather(
            name.encode(), arr.ctypes.data, self._dtype_id(arr), arr.ndim,
            self._dims(arr)), arr, name)

    def broadcast_async_(self, name: str, arr: np.ndarray, root: int) -> int:
        assert arr.flags["C_CONTIGUOUS"] and arr.flags["WRITEABLE"]
        return self._track(self.lib.hvdtpu_enqueue_broadcast(
            name.encode(), arr.ctypes.data, self._dtype_id(arr), arr.ndim,
            self._dims(arr), root), arr, name)

    # -- completion --------------------------------------------------------
    def poll(self, handle: int) -> bool:
        return bool(self.lib.hvdtpu_poll(handle))

    def _error(self, handle: int) -> str:
        n = self.lib.hvdtpu_error(handle, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self.lib.hvdtpu_error(handle, buf, n + 1)
        return buf.value.decode(errors="replace")

    def wait(self, handle: int, timeout: Optional[float] = None) -> None:
        """Block until done; raises NativeError on non-OK status.

        ``timeout`` (seconds; default: the HOROVOD_NEGOTIATION_TIMEOUT
        env knob, 0 = wait forever) bounds the wait: past the deadline a
        typed :class:`~horovod_tpu.common.exceptions.HorovodTimeoutError`
        is raised naming this rank and the stalled tensor. The op stays
        enqueued and its array stays pinned (the background thread may
        still write through the raw pointer), so the only safe recovery
        after a timeout is process exit — which is exactly what the
        elastic supervisor relaunch path does.
        """
        if timeout is None:
            timeout = self._default_timeout
        if timeout and timeout > 0:
            import time as _time

            deadline = _time.monotonic() + timeout
            pause = 0.0002
            while not self.lib.hvdtpu_poll(handle):
                if _time.monotonic() >= deadline:
                    from horovod_tpu.common.exceptions import \
                        HorovodTimeoutError

                    rank = self.rank()
                    name = self._names.get(handle, f"handle {handle}")
                    raise HorovodTimeoutError(
                        f"collective '{name}' did not complete within "
                        f"{timeout:g}s on rank {rank} "
                        "(HOROVOD_NEGOTIATION_TIMEOUT): a peer died or "
                        "skipped the collective. The op is still "
                        "in flight — exit this process and relaunch "
                        "(hvdrun --elastic resumes from the last "
                        "snapshot).", rank=rank, tensor_name=name)
                _time.sleep(pause)
                pause = min(pause * 2, 0.005)
        rc = self.lib.hvdtpu_wait(handle)
        if rc != StatusCode.OK:
            msg = self._error(handle)
            self.release(handle)
            raise NativeError(rc, msg)

    def take_result(self, handle: int, dtype, trailing_shape) -> np.ndarray:
        """Copy out an allgather result and release the handle."""
        nbytes = self.lib.hvdtpu_result_size(handle)
        if nbytes < 0:
            self.release(handle)
            raise NativeError(StatusCode.UNKNOWN_ERROR, "result missing")
        dtype = np.dtype(dtype)
        trailing = int(np.prod(trailing_shape)) if trailing_shape else 1
        row_bytes = dtype.itemsize * max(trailing, 1)
        if nbytes % row_bytes != 0:
            self.release(handle)
            raise NativeError(
                StatusCode.INVALID_ARGUMENT,
                f"allgather result of {nbytes} bytes is not divisible by "
                f"rows of {trailing} x {dtype} — dtype/trailing_shape do "
                "not match the gathered tensor")
        out = np.empty((nbytes // row_bytes, *trailing_shape), dtype=dtype)
        self.lib.hvdtpu_result_copy(handle, out.ctypes.data)
        self.release(handle)
        return out

    def release(self, handle: int) -> None:
        self.lib.hvdtpu_release(handle)
        with self._live_lock:
            self._live.pop(handle, None)
            self._names.pop(handle, None)

    # -- knobs + aux -------------------------------------------------------
    def set_fusion_threshold(self, nbytes: int) -> None:
        self.lib.hvdtpu_set_fusion_threshold(nbytes)

    def fusion_threshold(self) -> int:
        return self.lib.hvdtpu_fusion_threshold()

    def set_cycle_time_ms(self, ms: float) -> None:
        self.lib.hvdtpu_set_cycle_time_ms(ms)

    def cycle_time_ms(self) -> float:
        return self.lib.hvdtpu_cycle_time_ms()

    def timeline_start(self, path: str, mark_cycles: bool = False) -> None:
        self.lib.hvdtpu_timeline_start(path.encode(), int(mark_cycles))

    def timeline_end(self) -> None:
        self.lib.hvdtpu_timeline_end()

    def enable_autotune(self, log_path: str = "") -> None:
        self.lib.hvdtpu_enable_autotune(log_path.encode())
