"""Device-synchronization helper for timing harnesses.

On the tunneled axon backend, ``jax.block_until_ready`` does NOT wait
for device execution until the process has performed one device->host
transfer; before that first pull, "timed" regions measure async
dispatch only (~19x fast on the ResNet lane — PERF.md round-5 sync
trap). Every timing harness must call :func:`force_device_sync` after
warm-up and before its timed region; afterwards ``block_until_ready``
observes true completion and chained dispatch still pipelines.
"""

from __future__ import annotations


def force_device_sync(tree) -> float:
    """Pull one scalar off-device from any array leaf of ``tree``.

    Accepts a pytree (train state, grad tuple, single array). Returns
    the pulled scalar (summed in f32) so callers can also use it as a
    cheap checksum. No-op returning 0.0 when the tree has no array
    leaves.
    """
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    if not leaves:
        return 0.0
    leaf = leaves[0]
    if getattr(leaf, "is_fully_addressable", True) is False:
        # Multi-host: a global jax.Array spanning processes cannot be
        # consumed eagerly (jnp.sum raises on non-fully-addressable
        # input). Any d2h transfer flips the sync semantics, so pull
        # this process's first addressable shard instead.
        shards = leaf.addressable_shards
        if not shards:
            return 0.0
        leaf = shards[0].data
    return float(jnp.sum(leaf.astype(jnp.float32)))


def window_sync(tree, timeline=None, track: str = "hvd.window",
                steps=None) -> float:
    """One REAL device sync at a multi-step window boundary.

    ``block_until_ready`` + the d2h scalar pull of
    :func:`force_device_sync` (so the sync means what it says on the
    tunneled backend), with the whole span recorded on the Horovod
    timeline as ``WINDOW_SYNC`` when one is active — profiles of the
    window loop (horovod_tpu/jax/window.py) then attribute host time to
    dispatch vs boundary sync even though K steps share one program.
    Returns the pulled checksum scalar.
    """
    import jax

    tl_on = timeline is not None and getattr(timeline, "enabled", False)
    if tl_on:
        from horovod_tpu.utils.timeline import WINDOW_SYNC

        timeline.start(track, WINDOW_SYNC,
                       args=None if steps is None else {"steps": steps})
    try:
        jax.block_until_ready(tree)
        return force_device_sync(tree)
    finally:
        if tl_on:
            timeline.end(track, WINDOW_SYNC)
