"""Chrome-tracing timeline profiler.

TPU-native rebuild of the reference Horovod Timeline
(horovod/common/timeline.{h,cc}; semantics documented in the reference's
docs/timeline.md:17-62):

* activated by ``HOROVOD_TIMELINE=/path/trace.json``; rank-0 writes
  (reference operations.cc:1824-1829);
* per-tensor state machine NEGOTIATING -> TOP_LEVEL -> ACTIVITY
  (reference timeline.h:75-121);
* records never block the hot path: they are pushed onto a queue drained by
  a background writer thread (reference timeline.h:45-73 used a boost
  lock-free SPSC queue + writer thread; Python's ``SimpleQueue`` is the
  equivalent lock-free-enough primitive here — a C++ writer lives in
  csrc/timeline.cc for the native core);
* activity taxonomy kept from reference operations.h:29-50 with XLA-flavored
  additions.

The Chrome trace format is the "JSON Array Format": one event object per
line, comma-separated, '[' prologue — loadable in chrome://tracing and
Perfetto even when truncated mid-run (same property the reference relied on).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names (reference horovod/common/operations.h:29-50).
QUEUE = "QUEUE"
INIT_FUSION_BUFFER = "INIT_FUSION_BUFFER"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
ALLTOALL = "ALLTOALL"
# Overlap-shaped bucket reductions (horovod_tpu/jax/fusion.py): buckets
# above the scatter threshold split the allreduce into its ring halves —
# REDUCESCATTER then (after the sharded update) ALLGATHER — each its own
# activity under the bucket's ALLREDUCE span, which under overlap opens
# at collective ISSUE and closes at fusion-buffer UNPACK so the trace
# shows every in-flight bucket.
REDUCESCATTER = "REDUCESCATTER"
# XLA-path additions.
XLA_TRACE = "XLA_TRACE"
XLA_COMPILE = "XLA_COMPILE"
XLA_EXECUTE = "XLA_EXECUTE"
# Multi-step window activities (horovod_tpu/jax/window.py): WINDOW spans
# the ONE host dispatch of a K-step scanned window; WINDOW_SYNC spans the
# boundary block_until_ready + d2h pull, so a trace attributes host time
# to dispatch vs sync even when K steps share one program.
WINDOW = "WINDOW"
WINDOW_SYNC = "WINDOW_SYNC"

_NEGOTIATING = "NEGOTIATING"
_TOP_LEVEL = "TOP_LEVEL"


class Timeline:
    """Thread-safe, non-blocking chrome-trace writer.

    API mirrors the reference (timeline.h:83-93): ``negotiate_start/
    negotiate_rank_ready/negotiate_end``, ``start/activity_start/
    activity_end/end``, ``mark_cycle_start``.
    """

    def __init__(
        self,
        path: Optional[str],
        mark_cycles: bool = False,
        enabled_rank: bool = True,
    ) -> None:
        self._enabled = bool(path) and enabled_rank
        self._mark_cycles = mark_cycles
        self._path = path
        self._queue: "queue.SimpleQueue[Optional[dict]]" = queue.SimpleQueue()
        self._tensor_tracks: dict = {}
        self._next_tid = 1
        self._lock = threading.Lock()
        self._writer: Optional[threading.Thread] = None
        self._t0 = time.monotonic_ns()
        if self._enabled:
            self._writer = threading.Thread(
                target=self._drain, name="hvd-timeline-writer", daemon=True
            )
            self._writer.start()

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- infrastructure ----------------------------------------------------

    # Cap on named tracks so auto-named ops in long training loops cannot
    # grow the map unboundedly; overflow names share hashed tracks.
    _MAX_TRACKS = 4096

    def _now_us(self) -> float:
        return (time.monotonic_ns() - self._t0) / 1e3

    def _tid(self, tensor_name: str) -> int:
        with self._lock:
            tid = self._tensor_tracks.get(tensor_name)
            if tid is None:
                if self._next_tid > self._MAX_TRACKS:
                    return (hash(tensor_name) % self._MAX_TRACKS) + 1
                tid = self._next_tid
                self._next_tid += 1
                self._tensor_tracks[tensor_name] = tid
                self._queue.put(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": 0,
                        "tid": tid,
                        "args": {"name": tensor_name},
                    }
                )
            return tid

    def _emit(self, ev: dict) -> None:
        self._queue.put(ev)

    def _drain(self) -> None:
        assert self._path is not None
        with open(self._path, "w") as f:
            f.write("[\n")
            while True:
                ev = self._queue.get()
                if ev is None:
                    break
                f.write(json.dumps(ev))
                f.write(",\n")
                # Writer thread owns the file; flush per event batch is
                # acceptable off the hot path.
                if self._queue.empty():
                    f.flush()

    # -- reference API -----------------------------------------------------

    def negotiate_start(self, tensor_name: str, op: str) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": _NEGOTIATING,
                "ph": "B",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
                "args": {"op": op},
            }
        )

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": f"{rank}",
                "ph": "i",
                "s": "t",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
            }
        )

    def negotiate_end(self, tensor_name: str) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": _NEGOTIATING,
                "ph": "E",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
            }
        )

    def start(self, tensor_name: str, op: str,
              args: Optional[dict] = None) -> None:
        if not self._enabled:
            return
        ev = {
            "name": op,
            "ph": "B",
            "pid": 0,
            "tid": self._tid(tensor_name),
            "ts": self._now_us(),
        }
        if args:
            ev["args"] = args
        self._emit(ev)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": activity,
                "ph": "B",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
            }
        )

    def activity_end(self, tensor_name: str) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": "",
                "ph": "E",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
            }
        )

    def end(self, tensor_name: str, op: Optional[str] = None) -> None:
        if not self._enabled:
            return
        self._emit(
            {
                "name": op or "",
                "ph": "E",
                "pid": 0,
                "tid": self._tid(tensor_name),
                "ts": self._now_us(),
            }
        )

    def mark_window(self, index: int, steps: int) -> None:
        """Instant global marker at a multi-step window boundary
        (horovod_tpu/jax/window.py): the window-loop analogue of
        ``mark_cycle_start``, carrying the window index and the number
        of steps its single dispatch covers."""
        if not self._enabled:
            return
        self._emit(
            {
                "name": "WINDOW_START",
                "ph": "i",
                "s": "g",
                "pid": 0,
                "tid": 0,
                "ts": self._now_us(),
                "args": {"window": index, "steps": steps},
            }
        )

    def mark_cycle_start(self) -> None:
        # Reference: HOROVOD_TIMELINE_MARK_CYCLES (operations.cc:2042-2045).
        if self._enabled and self._mark_cycles:
            self._emit(
                {
                    "name": "CYCLE_START",
                    "ph": "i",
                    "s": "g",
                    "pid": 0,
                    "tid": 0,
                    "ts": self._now_us(),
                }
            )

    def close(self) -> None:
        if self._enabled and self._writer is not None:
            self._queue.put(None)
            self._writer.join(timeout=5.0)
            self._writer = None
            self._enabled = False


class _Activity:
    """Context manager sugar: ``with timeline.activity(name, ALLREDUCE): ...``"""

    def __init__(self, timeline: Timeline, tensor_name: str, activity: str):
        self._t = timeline
        self._name = tensor_name
        self._activity = activity

    def __enter__(self):
        self._t.activity_start(self._name, self._activity)
        return self

    def __exit__(self, *exc):
        self._t.activity_end(self._name)
        return False


def activity(timeline: Timeline, tensor_name: str, act: str) -> _Activity:
    return _Activity(timeline, tensor_name, act)
