"""Auxiliary subsystems: timeline, logging, autotune glue."""
