"""Attention kernels: reference jnp implementation + Pallas flash attention.

These are the single-chip building blocks under the sequence-parallel
schemes in :mod:`horovod_tpu.parallel` (ring attention rotates K/V blocks
between chips and calls a block kernel locally; Ulysses reshards heads and
calls a full local kernel). The reference framework has no attention ops —
long-context support is a first-class extension of this rebuild (SURVEY
§5 "Long-context / sequence parallelism: absent").

``flash_attention`` is a Pallas TPU kernel (online-softmax tiling so the
L x L score matrix never materializes in HBM); off-TPU it runs in
interpreter mode so tests cover the same code path. On the causal square
path all three streamed kernels (forward, dQ, dK/dV) execute a PACKED
at-or-below-diagonal grid — the strictly-masked half of the (q-block,
k-block) plane never occupies a grid step, so neither its K/V DMA bytes
nor its loop overhead is paid (closing the traffic debt PERF.md's
"Streamed-causal K/V traffic tradeoff" recorded).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30  # finite stand-in for -inf: exp() of it is exactly 0

# Measured dense/flash crossover on the LM lane (PERF.md round-5 honest
# adjudication #2): dense still wins at seq 2048 (-6%), flash wins 1.31x
# at seq 4096 and is the only structurally-compiling path beyond it.
# ``bench.py --attention auto`` selects by this threshold so nobody
# hand-picks the measured loser at either end.
FLASH_ATTENTION_MIN_SEQ = 4096


def dot_product_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset: int = 0, k_offset: int = 0):
    """Reference attention. Shapes: q [..., Lq, H, D], k/v [..., Lk, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first query/
    key token — block-parallel callers (ring attention) pass their shard's
    global offset so causal masks line up across chips.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-3])[:, None]
        ki = k_offset + jnp.arange(k.shape[-3])[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", weights.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Causal grid truncation policy (shared by kernels + accounting)


def _grid_truncates(causal: bool, seq_q: int, seq_k: int, q_offset: int,
                    k_offset: int, truncate: Optional[bool]) -> bool:
    """Static policy for the packed at-or-below-diagonal grid.

    It applies exactly when the mask is the standard square lower
    triangle: causal, Lq == Lk, equal global offsets. Cross-attention
    (Lq != Lk) and global-offset causal (ring shard geometry) keep the
    FULL grid with per-block compute skips — their diagonal can leave a
    q-block with zero live k-blocks, which a packed grid cannot
    represent (a block the grid never visits is never initialized or
    written). ``truncate=None`` is the auto policy; ``False`` forces
    the full grid (the truncated-vs-full A/B lanes); ``True`` asserts
    eligibility instead of silently degrading.
    """
    eligible = causal and seq_q == seq_k and q_offset == k_offset
    if truncate is None:
        return eligible
    if truncate and not eligible:
        raise ValueError(
            "truncate=True requires plain causal square attention "
            f"(causal={causal}, Lq={seq_q}, Lk={seq_k}, "
            f"q_offset={q_offset}, k_offset={k_offset}): cross-attention "
            "and offset-causal grids stay full (compute-skip only)")
    return bool(truncate)


@functools.lru_cache(maxsize=None)
def _causal_step_tables(n_qblocks: int, n_kblocks: int, block_q: int,
                        block_k: int, k_major: bool = False):
    """Scalar-prefetch step tables for the packed causal grid.

    Enumerates ONLY the (q-block, k-block) pairs that intersect the
    at-or-below-diagonal region (``qi*block_q + block_q - 1 >=
    kb*block_k``) — on an n x n grid with square blocks that is
    n(n+1)/2 of the n^2 full steps. q-major order streams k-blocks per
    q-block (forward + dQ); ``k_major`` streams q-blocks per k-block
    (dK/dV, whose dead region is the symmetric above-diagonal half over
    the q axis). Square-causal only: every q-block's first live k-block
    is 0 and every k-block's last live q-block is n_qblocks - 1, which
    is what the kernels' init/finalize conditions assume.
    """
    pairs = []
    if k_major:
        for kb in range(n_kblocks):
            # ceil((kb*bk - bq + 1) / bq) == floor(kb*bk / bq): the
            # first q-block whose last row reaches this k-block.
            pairs.extend((qi, kb)
                         for qi in range((kb * block_k) // block_q,
                                         n_qblocks))
    else:
        for qi in range(n_qblocks):
            last = min(n_kblocks - 1,
                       (qi * block_q + block_q - 1) // block_k)
            pairs.extend((qi, kb) for kb in range(last + 1))
    qi_tab = np.asarray([p[0] for p in pairs], np.int32)
    kb_tab = np.asarray([p[1] for p in pairs], np.int32)
    return qi_tab, kb_tab


def flash_grid_info(seq_q: int, seq_k: int, *, causal: bool,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    q_offset: int = 0, k_offset: int = 0,
                    truncate: Optional[bool] = None,
                    head_dim: Optional[int] = None,
                    batch_heads: int = 1, dtype_bytes: int = 2):
    """Static grid + K/V-DMA accounting for a ``flash_attention`` call.

    Mirrors exactly the tiling (:func:`_default_blocks`) and truncation
    (:func:`_grid_truncates`) policy the kernels use, without tracing
    anything — ``bench.py`` stamps this into the flash-lane JSON and
    ``tools/tpu_flash_check.py`` into its micro A/B report so every
    wall-time record is attributable to a concrete grid, not just a
    block pair.

    Returns a dict: chosen blocks, grid shape, per-``batch_heads``-step
    counts (``steps`` vs ``steps_full``), ``kv_fetch_frac`` (the
    truncated/full step ratio — (n+1)/2n on a causal square grid), and
    — when ``head_dim`` is given — the estimated K/V bytes the grid
    DMAs in (one [block_k, head_dim] tile each for K and V per step,
    times ``batch_heads``).
    """
    dq, dk = _default_blocks(seq_q, seq_k)
    bq = min(block_q if block_q is not None else dq, seq_q)
    bk = min(block_k if block_k is not None else dk, seq_k)
    nqb, nkb = seq_q // bq, seq_k // bk
    truncated = _grid_truncates(causal, seq_q, seq_k, q_offset, k_offset,
                                truncate)
    steps_full = nqb * nkb
    if truncated:
        qi_tab, _ = _causal_step_tables(nqb, nkb, bq, bk)
        steps = int(qi_tab.size)
    else:
        steps = steps_full
    info = {
        "block_q": bq, "block_k": bk,
        "n_qblocks": nqb, "n_kblocks": nkb,
        "truncated": truncated,
        "grid": ([batch_heads, steps] if truncated
                 else [batch_heads, nqb, nkb]),
        "steps": steps, "steps_full": steps_full,
        "kv_fetch_frac": round(steps / steps_full, 4),
        "kv_bytes": None, "kv_bytes_full": None,
    }
    if head_dim is not None:
        tile = 2 * bk * head_dim * dtype_bytes * batch_heads
        info["kv_bytes"] = steps * tile
        info["kv_bytes_full"] = steps_full * tile
    return info


# --------------------------------------------------------------------------
# Pallas flash attention


def _flash_kernel(*refs, block_k: int, n_kblocks: int, causal: bool,
                  scale: float, block_q: int, delta: int, packed: bool):
    """One streamed-forward grid step. Two grid layouts share this body:

    * full (``packed=False``) — grid (batch*head, q-block, K-BLOCK): the
      key axis rides the grid (innermost, "arbitrary" semantics), so
      Mosaic's pipeline streams [block_k, d] K/V tiles through
      double-buffered VMEM DMA while the online-softmax state (m/l/acc)
      persists in VMEM scratch across the k steps. Causal dead blocks
      skip their COMPUTE only — their K/V DMA is pipelined regardless.
    * packed (``packed=True``) — grid (batch*head, STEP) over the
      scalar-prefetched (q-block, k-block) tables of
      :func:`_causal_step_tables`: causal square grids enumerate only
      the at-or-below-diagonal pairs, so the dead half's DMA bytes and
      loop steps never exist. Every enumerated step is live — no
      compute skip needed; the diagonal block still applies the
      in-block row mask.

    ``delta = q_offset - k_offset`` shifts the causal mask for
    global-offset callers (always 0 on the packed path, which
    _grid_truncates restricts to equal offsets).

    VMEM is O(block) — the pre-streaming design mapped the FULL [Lk, d]
    K/V into each program's VMEM, which hit the 16 MB scoped limit at
    seq 16384 (tools/diag_seq16384.log: 16.25M > 16M).

    Mosaic discipline: every ref and all scratch is kept 2-D
    ([block_q, 1] for the m/l statistics, and the SAME [block_q, 1]
    shape for the lse output block — writing it as a [1, block_q] row
    would need a sublane->lane relayout inside the kernel, a classic
    Mosaic-unsupported reshape that interpret-mode CI cannot catch)."""
    from jax.experimental import pallas as pl

    if packed:
        qi_tab, kb_tab = refs[:2]
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs[2:]
        t = pl.program_id(1)
        qi = qi_tab[t]
        kb = kb_tab[t]
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
        qi = pl.program_id(1)
        kb = pl.program_id(2)

    # k-block 0 is the first step of every q-block in BOTH layouts (the
    # packed tables' q-major walk always starts a q-block at kb == 0).
    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        # Matmuls run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 inputs hit the MXU's native
        # bf16xbf16->f32 path (an f32xf32 matmul costs ~3 passes on
        # TPU); f32 test inputs keep the all-f32 exactness the CI pins.
        # All softmax statistics stay f32 regardless.
        q = q_ref[...]                              # [block_q, d]
        k_blk = k_ref[...]                          # [block_k, d]
        v_blk = v_ref[...]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = delta + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)

    if causal and not packed:
        # A k-block strictly past this q-block's last row is fully
        # masked: skip its compute (its DMA is pipelined regardless).
        pl.when(qi * block_q + block_q - 1 + delta
                >= kb * block_k)(_compute)
    else:
        _compute()  # packed grids enumerate live steps only

    if packed:
        last_kb = jnp.minimum(n_kblocks - 1,
                              (qi * block_q + block_q - 1) // block_k)
    else:
        last_kb = n_kblocks - 1

    @pl.when(kb == last_kb)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        # Per-row logsumexp (scores already include `scale`): persisted
        # so the backward never re-derives it with an extra pass over
        # the key blocks. Written in the statistics' native
        # [block_q, 1] layout — no cross-lane reshape inside the kernel.
        lse_ref[...] = m_scr[...] + jnp.log(l)


# Native TPU sublane tile: the f32 min tile is (8, 128), so blocks
# below 8 rows are rejected (or pathologically slow) by real Mosaic —
# interpret-mode CI would accept them and hide the hardware failure.
_MIN_BLOCK = 8


def _pick_block(cap: int, seq_len: int) -> int:
    """Largest ladder block <= cap that divides ``seq_len``, floored at
    the native 8-sublane tile.

    Lengths with no multiple-of-8 factor (L=100 -> old ladder degraded
    to 4; L=33 -> 1) are a caller error, not a tiling choice: raise the
    explicit "pad upstream" contract instead of emitting a sub-tile
    kernel that only fails once it reaches a chip (ADVICE r5 #1).
    """
    for b in (cap, 256, 128, 64, 32, 16, _MIN_BLOCK):
        if _MIN_BLOCK <= b <= cap and b <= seq_len and seq_len % b == 0:
            return b
    raise ValueError(
        f"flash_attention has no legal default block tile for sequence "
        f"length {seq_len}: no divisor >= the native {_MIN_BLOCK}-sublane "
        f"TPU tile. Pad the sequence length upstream to a multiple of "
        f"{_MIN_BLOCK} (ideally 128), or pass explicit block_q/block_k.")


def _default_blocks(seq_q: int, seq_k: int):
    """Measured tiling policy (TPU v5e block sweep, PERF.md round 5):
    256x512 won at seq 2048 (1.29x vs the old 128x128 default) and
    256x256 at seq 4096 (1.35x) — larger k-blocks amortize the online
    softmax rescale until the streamed K/V footprint presses VMEM, so
    the k-block steps down at longer key lengths. The q-block must
    divide the QUERY length and the k-block the KEY length (they differ
    for rectangular cross-attention / ring-attention shards), each
    degrading down a power-of-two ladder."""
    return (_pick_block(256, seq_q),
            _pick_block(512 if seq_k <= 2048 else 256, seq_k))


# Import-time default for the backward implementation ("scan" |
# "pallas" | "" = auto-by-length). Read ONCE so the selection is part
# of every trace's static key via the bwd_impl argument below —
# flipping the env mid-process cannot silently desync from cached
# traces; per-call control is the explicit bwd_impl= argument.
_FLASH_BWD_ENV_DEFAULT = __import__("os").environ.get("HVD_FLASH_BWD", "")


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret",
                                             "bwd_impl", "q_offset",
                                             "k_offset", "truncate"))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    bwd_impl: Optional[str] = None,
                    q_offset: int = 0, k_offset: int = 0,
                    truncate: Optional[bool] = None):
    """Pallas flash attention. Shapes [B, L, H, D] -> [B, L, H, D].

    Sequence lengths must be multiples of the block sizes (pad upstream).
    Block sizes default to the measured-on-TPU policy in
    :func:`_default_blocks`; pass explicit values to override.
    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.

    ``q_offset``/``k_offset`` (static) are the global positions of the
    first query/key token, matching :func:`dot_product_attention` — so
    sequence-parallel shims can call the kernel on a shard and keep the
    causal mask globally aligned. Plain causal square attention (equal
    offsets, Lq == Lk) executes a PACKED at-or-below-diagonal grid:
    ~(n+1)/2n of the full grid's steps, eliminating the dead half's K/V
    DMA bytes along with its loop overhead. Offset/rectangular causal
    keeps the full grid with per-block compute skips, and requires
    q_offset >= k_offset (every query row must see at least one key —
    rows with none have no defined softmax). ``truncate=False``
    forces the full grid (the truncated-vs-full A/B lanes);
    ``truncate=True`` asserts eligibility; the accounting twin is
    :func:`flash_grid_info`.

    Differentiable: the backward is two Pallas kernels (the
    FlashAttention-2 dQ / dK+dV split), recomputing scores blockwise
    against the forward's persisted logsumexp with O(block) VMEM per
    program — the [Lq, Lk] matrix is never materialized in either pass;
    both backward kernels ride the same truncated grid (the dK/dV dead
    region is the symmetric above-diagonal half over the q axis);
    gradient exactness vs the dense reference is pinned in
    tests/test_parallel.py::TestFlashAttention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk = _default_blocks(q.shape[1], k.shape[1])
    if block_q is None:
        block_q = dq
    if block_k is None:
        block_k = dk
    if bwd_impl is None:
        bwd_impl = _FLASH_BWD_ENV_DEFAULT or "auto"
    if bwd_impl not in ("auto", "scan", "pallas"):
        raise ValueError(f"bwd_impl must be auto|scan|pallas, "
                         f"got {bwd_impl!r}")
    if causal and q_offset < k_offset:
        # Query rows before the first key have NO unmasked key: their
        # softmax is undefined, and the kernels' 0-output would
        # silently diverge from the dense reference's degenerate
        # uniform-over-NEG_INF rows. A block-parallel caller whose
        # geometry straddles the diagonal this way needs partial-block
        # lse merging (the ring recurrence), not plain flash.
        raise ValueError(
            f"causal flash_attention requires q_offset >= k_offset "
            f"(got {q_offset} < {k_offset}): rows with no visible key "
            f"have no defined softmax")
    return _flash(q, k, v, causal, float(scale), block_q, block_k,
                  interpret, bwd_impl, int(q_offset), int(k_offset),
                  truncate)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, bwd_impl,
           q_offset, k_offset, truncate):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret, q_offset, k_offset, truncate)
    return out


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   q_offset=0, k_offset=0, truncate=None):
    """Returns (out [B, Lq, H, D], lse [B, H, Lq])."""
    from jax.experimental import pallas as pl

    from horovod_tpu.common.jax_compat import pallas_tpu
    pltpu = pallas_tpu()

    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    assert Lq % block_q == 0 and Lk % block_k == 0, (Lq, Lk, block_q, block_k)
    delta = q_offset - k_offset
    truncated = _grid_truncates(causal, Lq, Lk, q_offset, k_offset, truncate)

    # Collapse (B, H) into the grid's first axis; put seq minor-most for
    # contiguous VMEM tiles.
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    n_qblocks = Lq // block_q
    n_kblocks = Lk // block_k
    out_shape = [
        jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        jax.ShapeDtypeStruct((B * H, Lq, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
        pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
        pltpu.VMEM((block_q, D), jnp.float32),   # output accumulator
    ]
    if truncated:
        qi_tab, kb_tab = _causal_step_tables(n_qblocks, n_kblocks,
                                             block_q, block_k)
        kernel = functools.partial(_flash_kernel, block_k=block_k,
                                   n_kblocks=n_kblocks, causal=causal,
                                   scale=scale, block_q=block_q,
                                   delta=0, packed=True)
        # The STEP axis enumerates only the live at-or-below-diagonal
        # (q-block, k-block) pairs — ~(n+1)/2n of the full causal grid.
        # Still sequential ("arbitrary") so the scratch-carried softmax
        # state is legal, and Mosaic double-buffers exactly the
        # [block_k, D] K/V tile DMAs the mask actually needs; the
        # block indices come off the scalar-prefetched tables.
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, int(qi_tab.size)),
            in_specs=[
                pl.BlockSpec((None, block_q, D),
                             lambda bh, t, qi, kb: (bh, qi[t], 0)),
                pl.BlockSpec((None, block_k, D),
                             lambda bh, t, qi, kb: (bh, kb[t], 0)),
                pl.BlockSpec((None, block_k, D),
                             lambda bh, t, qi, kb: (bh, kb[t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, D),
                             lambda bh, t, qi, kb: (bh, qi[t], 0)),
                # [block_q, 1] column per program — the statistics'
                # native layout (see the kernel's Mosaic-discipline
                # note); the trailing singleton is dropped OUTSIDE the
                # kernel where a relayout is just an XLA reshape.
                pl.BlockSpec((None, block_q, 1),
                             lambda bh, t, qi, kb: (bh, qi[t], 0)),
            ],
            scratch_shapes=scratch,
        )
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(qi_tab), jnp.asarray(kb_tab), qr, kr, vr)
    else:
        kernel = functools.partial(_flash_kernel, block_k=block_k,
                                   n_kblocks=n_kblocks, causal=causal,
                                   scale=scale, block_q=block_q,
                                   delta=delta, packed=False)
        out, lse = pl.pallas_call(
            kernel,
            # K blocks ride the grid's INNERMOST axis: sequential
            # ("arbitrary") so the scratch-carried softmax state is
            # legal, while Mosaic double-buffers the [block_k, D] K/V
            # tile DMAs.
            grid=(B * H, n_qblocks, n_kblocks),
            in_specs=[
                pl.BlockSpec((None, block_q, D),
                             lambda bh, qb, kb: (bh, qb, 0)),
                pl.BlockSpec((None, block_k, D),
                             lambda bh, qb, kb: (bh, kb, 0)),
                pl.BlockSpec((None, block_k, D),
                             lambda bh, qb, kb: (bh, kb, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, D),
                             lambda bh, qb, kb: (bh, qb, 0)),
                pl.BlockSpec((None, block_q, 1),
                             lambda bh, qb, kb: (bh, qb, 0)),
            ],
            out_shape=out_shape,
            scratch_shapes=scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qr, kr, vr)
    return (out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3),
            lse.reshape(B, H, Lq))


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret,
                   bwd_impl, q_offset, k_offset, truncate):
    o, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret, q_offset, k_offset, truncate)
    return o, (q, k, v, o, lse)


def _flash_bwd_dq_kernel(*refs, causal: bool, scale: float, block_q: int,
                         block_k: int, n_kblocks: int, delta: int,
                         packed: bool):
    """dQ: full grid (batch*head, q-block, K-BLOCK stream) or the packed
    q-major causal grid (batch*head, STEP) — same layout split as
    :func:`_flash_kernel`. Standard FlashAttention-2 recurrence against
    the forward's persisted logsumexp:
        P_ij = exp(S_ij - lse_i);  dS_ij = P_ij * (dO_i V_j^T - D_i)
        dQ_i = sum_j dS_ij K_j * scale
    The k axis rides the grid (sequential) with the dQ accumulator in
    VMEM scratch — same O(block) VMEM shape as the forward kernel."""
    from jax.experimental import pallas as pl

    if packed:
        qi_tab, kb_tab = refs[:2]
        (q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
         dq_ref, dq_scr) = refs[2:]
        t = pl.program_id(1)
        qi = qi_tab[t]
        kb = kb_tab[t]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
         dq_ref, dq_scr) = refs
        qi = pl.program_id(1)
        kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        # Input-dtype matmuls, f32 accumulation (see _flash_kernel).
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        do_blk = do_ref[...]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = delta + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                    # [bq, bk]
        dp = jnp.dot(do_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[...])
        dq_scr[...] += jnp.dot(ds.astype(k_blk.dtype), k_blk,
                               preferred_element_type=jnp.float32) * scale

    if causal and not packed:
        pl.when(qi * block_q + block_q - 1 + delta
                >= kb * block_k)(_compute)
    else:
        _compute()  # packed grids enumerate live steps only

    if packed:
        last_kb = jnp.minimum(n_kblocks - 1,
                              (qi * block_q + block_q - 1) // block_k)
    else:
        last_kb = n_kblocks - 1

    @pl.when(kb == last_kb)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, causal: bool, scale: float, block_q: int,
                          block_k: int, n_qblocks: int, delta: int,
                          packed: bool):
    """dK/dV: full grid (batch*head, k-block, Q-BLOCK stream) or the
    packed K-MAJOR causal grid — transposing the dQ kernel's roles, so
    the truncated region is the symmetric above-diagonal half over the
    q axis (each k-block's stream starts at its diagonal q-block):
        dV_j = sum_i P_ij^T dO_i;  dK_j = sum_i dS_ij^T Q_i * scale"""
    from jax.experimental import pallas as pl

    if packed:
        qi_tab, kb_tab = refs[:2]
        (q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs[2:]
        t = pl.program_id(1)
        qi = qi_tab[t]
        kb = kb_tab[t]
        # First live q-block of this k-block's stream: the diagonal
        # (matches _causal_step_tables' k-major start).
        first_qi = (kb * block_k) // block_q
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        kb = pl.program_id(1)
        qi = pl.program_id(2)
        first_qi = 0

    @pl.when(qi == first_qi)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        # Input-dtype matmuls, f32 accumulation (see _flash_kernel).
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        do_blk = do_ref[...]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = delta + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                    # [bq, bk]
        dv_scr[...] += jnp.dot(p.T.astype(do_blk.dtype), do_blk,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[...])
        dk_scr[...] += jnp.dot(ds.T.astype(q.dtype), q,
                               preferred_element_type=jnp.float32) * scale

    if causal and not packed:
        # Q-blocks fully ABOVE the diagonal (every q_pos < every k_pos)
        # contribute nothing to this k-block.
        pl.when(qi * block_q + block_q - 1 + delta
                >= kb * block_k)(_compute)
    else:
        _compute()  # packed grids enumerate live steps only

    @pl.when(qi == n_qblocks - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_scan(causal, scale, block_q, block_k, interpret,
                    q_offset, k_offset, truncate, res, do):
    """XLA lax.scan backward (the pre-round-5 implementation, kept as a
    selectable path): one batched einsum pass per key block computing
    dq/dk/dv together. At seq <= ~4096 its [B, H, Lq, block_k] einsum
    slabs are MXU-friendly batched matmuls and it MEASURES faster than
    the kernel split (10.45M vs 9.68M tok/s at seq 2048, PERF.md r5);
    at long seq those slabs become multi-hundred-MB HBM round-trips
    per block step. Selected by ``HVD_FLASH_BWD=scan`` or
    automatically at short key lengths (see _flash_bwd_vjp). Already
    grid-truncated by construction: the causal scan walks only the
    k-blocks at or below the last query row's diagonal (``truncate``
    is accepted for signature parity and ignored)."""
    del truncate  # no grid to truncate: the scan bound below early-exits
    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bk = min(block_k, Lk)
    nkb = Lk // bk
    delta = q_offset - k_offset
    if causal:
        # Keys past the last query row's global position are dead for
        # every row; at least one block stays so the scan is non-empty.
        nkb_live = min(nkb, max(0, delta + Lq - 1) // bk + 1)
        nkb_live = max(1, nkb_live)
    else:
        nkb_live = nkb
    # Einsums run in the input dtype with f32 accumulation
    # (preferred_element_type) — bf16 inputs keep the MXU's native
    # path; f32 test inputs keep CI exactness. Softmax stats stay f32.
    f32 = jnp.float32
    d_row = jnp.sum(do.astype(f32) * o.astype(f32), axis=-1)  # [B, Lq, H]
    d_row = d_row.transpose(0, 2, 1)                           # [B, H, Lq]
    q_pos = delta + jnp.arange(Lq)[:, None]

    def bwd_step(dq, jb):
        kb = jax.lax.dynamic_slice_in_dim(k, jb * bk, bk, 1)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=f32) * scale
        if causal:
            k_pos = jb * bk + jnp.arange(bk)[None, :]
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        vb = jax.lax.dynamic_slice_in_dim(v, jb * bk, bk, 1)
        p = jnp.exp(s - lse[..., None])                     # [B,H,Lq,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", do, vb,
                        preferred_element_type=f32)
        ds = p * (dp - d_row[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds.astype(k.dtype), kb,
                             preferred_element_type=f32) * scale
        dkb = jnp.einsum("bhqk,bqhd->bkhd", ds.astype(q.dtype), q,
                         preferred_element_type=f32) * scale
        dvb = jnp.einsum("bhqk,bqhd->bkhd", p.astype(do.dtype), do,
                         preferred_element_type=f32)
        return dq, (dkb, dvb)

    dq, (dks, dvs) = jax.lax.scan(
        bwd_step, jnp.zeros(q.shape, jnp.float32), jnp.arange(nkb_live))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    if nkb_live < nkb:
        pad = [(0, 0), (0, Lk - nkb_live * bk), (0, 0), (0, 0)]
        dk = jnp.pad(dk, pad)
        dv = jnp.pad(dv, pad)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_pallas(causal, scale, block_q, block_k, interpret,
                      q_offset, k_offset, truncate, res, do):
    """Flash backward as two Pallas kernels (FlashAttention-2 split):
    a dQ kernel streaming k-blocks and a dK/dV kernel streaming
    q-blocks, both against the forward's persisted logsumexp and the
    precomputed row dot D_i = rowsum(dO_i * O_i). The score matrix is
    never materialized; VMEM is O(block) per program, so the backward
    scales to the same contexts the streamed forward unlocked (the
    prior lax.scan backward materialized [B, H, Lq, block_k] slabs in
    HBM per step — 2 GB at seq 16k — and serialized the k-block walk).
    On the causal square path both kernels ride the PACKED grid of
    :func:`_causal_step_tables` (q-major for dQ, k-major for dK/dV), so
    the dead half of each grid — ~2x the K/V and Q/dO bytes actually
    needed — is never DMA'd. For causal rectangular/offset Lq != Lk the
    grids stay full and blocks entirely on the masked side of the
    diagonal skip their compute only."""
    from jax.experimental import pallas as pl

    from horovod_tpu.common.jax_compat import pallas_tpu
    pltpu = pallas_tpu()

    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    assert Lq % bq == 0 and Lk % bk == 0, (Lq, Lk, bq, bk)
    nqb, nkb = Lq // bq, Lk // bk
    delta = q_offset - k_offset
    truncated = _grid_truncates(causal, Lq, Lk, q_offset, k_offset, truncate)

    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    dor = do.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    # lse arrives [B, H, Lq]; D_i rowsum in fp32. Both as [bh, Lq, 1]
    # columns — the statistics' native kernel layout.
    lser = lse.reshape(B * H, Lq, 1)
    d_row = jnp.sum(dor.astype(jnp.float32)
                    * o.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
                    .astype(jnp.float32), axis=-1, keepdims=True)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, causal=causal, scale=scale, block_q=bq,
        block_k=bk, n_kblocks=nkb, delta=0 if truncated else delta,
        packed=truncated)
    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, causal=causal, scale=scale, block_q=bq,
        block_k=bk, n_qblocks=nqb, delta=0 if truncated else delta,
        packed=truncated)
    dq_out_shape = jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype)
    dkv_out_shape = [
        jax.ShapeDtypeStruct((B * H, Lk, D), k.dtype),
        jax.ShapeDtypeStruct((B * H, Lk, D), v.dtype),
    ]

    if truncated:
        # Packed causal grids: q-major steps for dQ (k-blocks stream
        # within a q-block), k-major for dK/dV (q-blocks stream within
        # a k-block, starting at the diagonal).
        qi_q, kb_q = _causal_step_tables(nqb, nkb, bq, bk)
        qi_k, kb_k = _causal_step_tables(nqb, nkb, bq, bk, k_major=True)
        qspec = pl.BlockSpec((None, bq, D),
                             lambda bh, t, qi, kb: (bh, qi[t], 0))
        kspec = pl.BlockSpec((None, bk, D),
                             lambda bh, t, qi, kb: (bh, kb[t], 0))
        col_q = pl.BlockSpec((None, bq, 1),
                             lambda bh, t, qi, kb: (bh, qi[t], 0))
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, int(qi_q.size)),
                in_specs=[qspec, kspec, kspec, qspec, col_q, col_q],
                out_specs=qspec,
                scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
            out_shape=dq_out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(qi_q), jnp.asarray(kb_q), qr, kr, vr, dor, lser,
          d_row)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, int(qi_k.size)),
                in_specs=[qspec, kspec, kspec, qspec, col_q, col_q],
                out_specs=[kspec, kspec],
                scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                                pltpu.VMEM((bk, D), jnp.float32)]),
            out_shape=dkv_out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(qi_k), jnp.asarray(kb_k), qr, kr, vr, dor, lser,
          d_row)
    else:
        qspec = pl.BlockSpec((None, bq, D), lambda bh, i, j: (bh, i, 0))
        kspec = pl.BlockSpec((None, bk, D), lambda bh, i, j: (bh, j, 0))
        col_q = pl.BlockSpec((None, bq, 1), lambda bh, i, j: (bh, i, 0))
        dq = pl.pallas_call(
            dq_kernel,
            grid=(B * H, nqb, nkb),
            in_specs=[qspec, kspec, kspec, qspec, col_q, col_q],
            out_specs=pl.BlockSpec((None, bq, D),
                                   lambda bh, i, j: (bh, i, 0)),
            out_shape=dq_out_shape,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qr, kr, vr, dor, lser, d_row)

        # dK/dV grid transposes the stream: (bh, k-block, q-stream).
        qspec_t = pl.BlockSpec((None, bq, D), lambda bh, j, i: (bh, i, 0))
        kspec_t = pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0))
        col_q_t = pl.BlockSpec((None, bq, 1), lambda bh, j, i: (bh, i, 0))
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(B * H, nkb, nqb),
            in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, col_q_t,
                      col_q_t],
            out_specs=[
                pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0)),
                pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0)),
            ],
            out_shape=dkv_out_shape,
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qr, kr, vr, dor, lser, d_row)

    def unflat(t, L):
        return t.reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return unflat(dq, Lq), unflat(dk, Lk), unflat(dv, Lk)


# Key length at/above which the kernel backward takes over from the
# scan backward by default (measured crossover, PERF.md round 5).
_FLASH_BWD_PALLAS_MIN_LK = 8192


def resolve_bwd_impl(bwd_impl: Optional[str], seq_k: int) -> str:
    """The backward implementation a flash_attention call will actually
    run, mirroring flash_attention's own dispatch: None defers to the
    HVD_FLASH_BWD import-time env default, then "auto" picks the
    measured crossover — the scan backward below the
    _FLASH_BWD_PALLAS_MIN_LK key length, the Pallas kernel split
    at/above. Public so bench.py can stamp the RESOLVED backward into
    flash-lane records: the truncated-vs-full grid A/B only spans the
    backward when this says "pallas" (the scan walk is
    diagonal-truncated by construction on both sides)."""
    if bwd_impl is None:
        bwd_impl = _FLASH_BWD_ENV_DEFAULT or "auto"
    if bwd_impl == "auto":
        return ("pallas" if seq_k >= _FLASH_BWD_PALLAS_MIN_LK
                else "scan")
    return bwd_impl


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, bwd_impl,
                   q_offset, k_offset, truncate, res, do):
    """Backward dispatch, measured not assumed (PERF.md round 5): the
    scan backward's batched einsums win at short key lengths; the
    O(block)-VMEM kernel split is required at long ones (the scan's
    per-block [B, H, Lq, block_k] slabs scale with Lq). ``bwd_impl``
    arrives as a static ("auto"|"scan"|"pallas") from flash_attention —
    part of the trace key, so selection can never desync from a cached
    trace."""
    impl = resolve_bwd_impl(bwd_impl, res[1].shape[1])
    fn = _flash_bwd_pallas if impl == "pallas" else _flash_bwd_scan
    return fn(causal, scale, block_q, block_k, interpret,
              q_offset, k_offset, truncate, res, do)


_flash.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
