"""Attention kernels: reference jnp implementation + Pallas flash attention.

These are the single-chip building blocks under the sequence-parallel
schemes in :mod:`horovod_tpu.parallel` (ring attention rotates K/V blocks
between chips and calls a block kernel locally; Ulysses reshards heads and
calls a full local kernel). The reference framework has no attention ops —
long-context support is a first-class extension of this rebuild (SURVEY
§5 "Long-context / sequence parallelism: absent").

``flash_attention`` is a Pallas TPU kernel (online-softmax tiling so the
L x L score matrix never materializes in HBM); off-TPU it runs in
interpreter mode so tests cover the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite stand-in for -inf: exp() of it is exactly 0


def dot_product_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset: int = 0, k_offset: int = 0):
    """Reference attention. Shapes: q [..., Lq, H, D], k/v [..., Lk, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first query/
    key token — block-parallel callers (ring attention) pass their shard's
    global offset so causal masks line up across chips.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-3])[:, None]
        ki = k_offset + jnp.arange(k.shape[-3])[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", weights.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Pallas flash attention


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, block_k: int, n_kblocks: int, causal: bool,
                  scale: float, block_q: int):
    """One (batch*head, q-block, K-BLOCK) grid step: the key axis rides
    the grid (innermost, "arbitrary" semantics), so Mosaic's pipeline
    streams [block_k, d] K/V tiles through double-buffered VMEM DMA
    while the online-softmax state (m/l/acc) persists in VMEM scratch
    across the k steps. VMEM is O(block) — the previous design mapped
    the FULL [Lk, d] K/V into each program's VMEM, which hit the 16 MB
    scoped limit at seq 16384 (tools/diag_seq16384.log: 16.25M > 16M).

    Mosaic discipline: every ref and all scratch is kept 2-D
    ([block_q, 1] for the m/l statistics, and the SAME [block_q, 1]
    shape for the lse output block — writing it as a [1, block_q] row
    would need a sublane->lane relayout inside the kernel, a classic
    Mosaic-unsupported reshape that interpret-mode CI cannot catch)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        # Matmuls run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 inputs hit the MXU's native
        # bf16xbf16->f32 path (an f32xf32 matmul costs ~3 passes on
        # TPU); f32 test inputs keep the all-f32 exactness the CI pins.
        # All softmax statistics stay f32 regardless.
        q = q_ref[...]                              # [block_q, d]
        k_blk = k_ref[...]                          # [block_k, d]
        v_blk = v_ref[...]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)

    if causal:
        # A k-block strictly past this q-block's last row is fully
        # masked: skip its compute (its DMA is pipelined regardless).
        pl.when(qi * block_q + block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        # Per-row logsumexp (scores already include `scale`): persisted
        # so the backward never re-derives it with an extra pass over
        # the key blocks. Written in the statistics' native
        # [block_q, 1] layout — no cross-lane reshape inside the kernel.
        lse_ref[...] = m_scr[...] + jnp.log(l)


# Native TPU sublane tile: the f32 min tile is (8, 128), so blocks
# below 8 rows are rejected (or pathologically slow) by real Mosaic —
# interpret-mode CI would accept them and hide the hardware failure.
_MIN_BLOCK = 8


def _pick_block(cap: int, seq_len: int) -> int:
    """Largest ladder block <= cap that divides ``seq_len``, floored at
    the native 8-sublane tile.

    Lengths with no multiple-of-8 factor (L=100 -> old ladder degraded
    to 4; L=33 -> 1) are a caller error, not a tiling choice: raise the
    explicit "pad upstream" contract instead of emitting a sub-tile
    kernel that only fails once it reaches a chip (ADVICE r5 #1).
    """
    for b in (cap, 256, 128, 64, 32, 16, _MIN_BLOCK):
        if _MIN_BLOCK <= b <= cap and b <= seq_len and seq_len % b == 0:
            return b
    raise ValueError(
        f"flash_attention has no legal default block tile for sequence "
        f"length {seq_len}: no divisor >= the native {_MIN_BLOCK}-sublane "
        f"TPU tile. Pad the sequence length upstream to a multiple of "
        f"{_MIN_BLOCK} (ideally 128), or pass explicit block_q/block_k.")


def _default_blocks(seq_q: int, seq_k: int):
    """Measured tiling policy (TPU v5e block sweep, PERF.md round 5):
    256x512 won at seq 2048 (1.29x vs the old 128x128 default) and
    256x256 at seq 4096 (1.35x) — larger k-blocks amortize the online
    softmax rescale until the streamed K/V footprint presses VMEM, so
    the k-block steps down at longer key lengths. The q-block must
    divide the QUERY length and the k-block the KEY length (they differ
    for rectangular cross-attention / ring-attention shards), each
    degrading down a power-of-two ladder."""
    return (_pick_block(256, seq_q),
            _pick_block(512 if seq_k <= 2048 else 256, seq_k))


# Import-time default for the backward implementation ("scan" |
# "pallas" | "" = auto-by-length). Read ONCE so the selection is part
# of every trace's static key via the bwd_impl argument below —
# flipping the env mid-process cannot silently desync from cached
# traces; per-call control is the explicit bwd_impl= argument.
_FLASH_BWD_ENV_DEFAULT = __import__("os").environ.get("HVD_FLASH_BWD", "")


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret",
                                             "bwd_impl"))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    bwd_impl: Optional[str] = None):
    """Pallas flash attention. Shapes [B, L, H, D] -> [B, L, H, D].

    Sequence lengths must be multiples of the block sizes (pad upstream).
    Block sizes default to the measured-on-TPU policy in
    :func:`_default_blocks`; pass explicit values to override.
    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.

    Differentiable: the backward is two Pallas kernels (the
    FlashAttention-2 dQ / dK+dV split), recomputing scores blockwise
    against the forward's persisted logsumexp with O(block) VMEM per
    program — the [Lq, Lk] matrix is never materialized in either pass;
    gradient exactness vs the dense reference is pinned in
    tests/test_parallel.py::TestFlashAttention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk = _default_blocks(q.shape[1], k.shape[1])
    if block_q is None:
        block_q = dq
    if block_k is None:
        block_k = dk
    if bwd_impl is None:
        bwd_impl = _FLASH_BWD_ENV_DEFAULT or "auto"
    if bwd_impl not in ("auto", "scan", "pallas"):
        raise ValueError(f"bwd_impl must be auto|scan|pallas, "
                         f"got {bwd_impl!r}")
    return _flash(q, k, v, causal, float(scale), block_q, block_k,
                  interpret, bwd_impl)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, bwd_impl):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return out


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (out [B, Lq, H, D], lse [B, H, Lq])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    assert Lq % block_q == 0 and Lk % block_k == 0, (Lq, Lk, block_q, block_k)

    # Collapse (B, H) into the grid's first axis; put seq minor-most for
    # contiguous VMEM tiles.
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    n_kblocks = Lk // block_k
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               n_kblocks=n_kblocks, causal=causal,
                               scale=scale, block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        # K blocks ride the grid's INNERMOST axis: sequential
        # ("arbitrary") so the scratch-carried softmax state is legal,
        # while Mosaic double-buffers the [block_k, D] K/V tile DMAs.
        grid=(B * H, Lq // block_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, qb, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb, kb: (bh, qb, 0)),
            # [block_q, 1] column per program — the statistics' native
            # layout (see the kernel's Mosaic-discipline note); the
            # trailing singleton is dropped OUTSIDE the kernel where a
            # relayout is just an XLA reshape.
            pl.BlockSpec((None, block_q, 1), lambda bh, qb, kb: (bh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, D), jnp.float32),   # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qr, kr, vr)
    return (out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3),
            lse.reshape(B, H, Lq))


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret,
                   bwd_impl):
    o, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                         dq_ref, dq_scr, *, causal: bool, scale: float,
                         block_q: int, block_k: int, n_kblocks: int):
    """dQ: grid (batch*head, q-block, K-BLOCK stream). Standard
    FlashAttention-2 recurrence against the forward's persisted
    logsumexp:
        P_ij = exp(S_ij - lse_i);  dS_ij = P_ij * (dO_i V_j^T - D_i)
        dQ_i = sum_j dS_ij K_j * scale
    The k axis rides the grid (sequential) with the dQ accumulator in
    VMEM scratch — same O(block) VMEM shape as the forward kernel."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        # Input-dtype matmuls, f32 accumulation (see _flash_kernel).
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        do_blk = do_ref[...]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                    # [bq, bk]
        dp = jnp.dot(do_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[...])
        dq_scr[...] += jnp.dot(ds.astype(k_blk.dtype), k_blk,
                               preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(qi * block_q + block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          n_qblocks: int):
    """dK/dV: grid (batch*head, k-block, Q-BLOCK stream), transposing
    the dQ kernel's roles:
        dV_j = sum_i P_ij^T dO_i;  dK_j = sum_i dS_ij^T Q_i * scale"""
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        # Input-dtype matmuls, f32 accumulation (see _flash_kernel).
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        do_blk = do_ref[...]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                    # [bq, bk]
        dv_scr[...] += jnp.dot(p.T.astype(do_blk.dtype), do_blk,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[...])
        dk_scr[...] += jnp.dot(ds.T.astype(q.dtype), q,
                               preferred_element_type=jnp.float32) * scale

    if causal:
        # Q-blocks fully ABOVE the diagonal (every q_pos < every k_pos)
        # contribute nothing to this k-block.
        pl.when(qi * block_q + block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == n_qblocks - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_scan(causal, scale, block_q, block_k, interpret, res, do):
    """XLA lax.scan backward (the pre-round-5 implementation, kept as a
    selectable path): one batched einsum pass per key block computing
    dq/dk/dv together. At seq <= ~4096 its [B, H, Lq, block_k] einsum
    slabs are MXU-friendly batched matmuls and it MEASURES faster than
    the kernel split (10.45M vs 9.68M tok/s at seq 2048, PERF.md r5);
    at long seq those slabs become multi-hundred-MB HBM round-trips
    per block step. Selected by ``HVD_FLASH_BWD=scan`` or
    automatically at short key lengths (see _flash_bwd_vjp)."""
    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bk = min(block_k, Lk)
    nkb = Lk // bk
    nkb_live = min(nkb, -(-Lq // bk)) if causal else nkb
    # Einsums run in the input dtype with f32 accumulation
    # (preferred_element_type) — bf16 inputs keep the MXU's native
    # path; f32 test inputs keep CI exactness. Softmax stats stay f32.
    f32 = jnp.float32
    d_row = jnp.sum(do.astype(f32) * o.astype(f32), axis=-1)  # [B, Lq, H]
    d_row = d_row.transpose(0, 2, 1)                           # [B, H, Lq]
    q_pos = jnp.arange(Lq)[:, None]

    def bwd_step(dq, jb):
        kb = jax.lax.dynamic_slice_in_dim(k, jb * bk, bk, 1)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=f32) * scale
        if causal:
            k_pos = jb * bk + jnp.arange(bk)[None, :]
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        vb = jax.lax.dynamic_slice_in_dim(v, jb * bk, bk, 1)
        p = jnp.exp(s - lse[..., None])                     # [B,H,Lq,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", do, vb,
                        preferred_element_type=f32)
        ds = p * (dp - d_row[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds.astype(k.dtype), kb,
                             preferred_element_type=f32) * scale
        dkb = jnp.einsum("bhqk,bqhd->bkhd", ds.astype(q.dtype), q,
                         preferred_element_type=f32) * scale
        dvb = jnp.einsum("bhqk,bqhd->bkhd", p.astype(do.dtype), do,
                         preferred_element_type=f32)
        return dq, (dkb, dvb)

    dq, (dks, dvs) = jax.lax.scan(
        bwd_step, jnp.zeros(q.shape, jnp.float32), jnp.arange(nkb_live))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    if nkb_live < nkb:
        pad = [(0, 0), (0, Lk - nkb_live * bk), (0, 0), (0, 0)]
        dk = jnp.pad(dk, pad)
        dv = jnp.pad(dv, pad)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_pallas(causal, scale, block_q, block_k, interpret, res, do):
    """Flash backward as two Pallas kernels (FlashAttention-2 split):
    a dQ kernel streaming k-blocks and a dK/dV kernel streaming
    q-blocks, both against the forward's persisted logsumexp and the
    precomputed row dot D_i = rowsum(dO_i * O_i). The score matrix is
    never materialized; VMEM is O(block) per program, so the backward
    scales to the same contexts the streamed forward unlocked (the
    prior lax.scan backward materialized [B, H, Lq, block_k] slabs in
    HBM per step — 2 GB at seq 16k — and serialized the k-block walk).
    For causal rectangular Lq != Lk, blocks entirely on the masked side
    of the diagonal skip their compute in both kernels."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    assert Lq % bq == 0 and Lk % bk == 0, (Lq, Lk, bq, bk)
    nqb, nkb = Lq // bq, Lk // bk

    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    dor = do.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    # lse arrives [B, H, Lq]; D_i rowsum in fp32. Both as [bh, Lq, 1]
    # columns — the statistics' native kernel layout.
    lser = lse.reshape(B * H, Lq, 1)
    d_row = jnp.sum(dor.astype(jnp.float32)
                    * o.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
                    .astype(jnp.float32), axis=-1, keepdims=True)

    qspec = pl.BlockSpec((None, bq, D), lambda bh, i, j: (bh, i, 0))
    kspec = pl.BlockSpec((None, bk, D), lambda bh, i, j: (bh, j, 0))
    col_q = pl.BlockSpec((None, bq, 1), lambda bh, i, j: (bh, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, n_kblocks=nkb),
        grid=(B * H, nqb, nkb),
        in_specs=[qspec, kspec, kspec, qspec, col_q, col_q],
        out_specs=pl.BlockSpec((None, bq, D), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, d_row)

    # dK/dV grid transposes the stream: (bh, k-block, q-stream).
    qspec_t = pl.BlockSpec((None, bq, D), lambda bh, j, i: (bh, i, 0))
    kspec_t = pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0))
    col_q_t = pl.BlockSpec((None, bq, 1), lambda bh, j, i: (bh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                          scale=scale, block_q=bq, block_k=bk,
                          n_qblocks=nqb),
        grid=(B * H, nkb, nqb),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, col_q_t, col_q_t],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((None, bk, D), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Lk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, d_row)

    def unflat(t, L):
        return t.reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return unflat(dq, Lq), unflat(dk, Lk), unflat(dv, Lk)


# Key length at/above which the kernel backward takes over from the
# scan backward by default (measured crossover, PERF.md round 5).
_FLASH_BWD_PALLAS_MIN_LK = 8192


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, bwd_impl,
                   res, do):
    """Backward dispatch, measured not assumed (PERF.md round 5): the
    scan backward's batched einsums win at short key lengths; the
    O(block)-VMEM kernel split is required at long ones (the scan's
    per-block [B, H, Lq, block_k] slabs scale with Lq). ``bwd_impl``
    arrives as a static ("auto"|"scan"|"pallas") from flash_attention —
    part of the trace key, so selection can never desync from a cached
    trace."""
    impl = bwd_impl
    if impl == "auto":
        impl = ("pallas" if res[1].shape[1] >= _FLASH_BWD_PALLAS_MIN_LK
                else "scan")
    fn = _flash_bwd_pallas if impl == "pallas" else _flash_bwd_scan
    return fn(causal, scale, block_q, block_k, interpret, res, do)


_flash.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
