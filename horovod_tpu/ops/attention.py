"""Attention kernels: reference jnp implementation + Pallas flash attention.

These are the single-chip building blocks under the sequence-parallel
schemes in :mod:`horovod_tpu.parallel` (ring attention rotates K/V blocks
between chips and calls a block kernel locally; Ulysses reshards heads and
calls a full local kernel). The reference framework has no attention ops —
long-context support is a first-class extension of this rebuild (SURVEY
§5 "Long-context / sequence parallelism: absent").

``flash_attention`` is a Pallas TPU kernel (online-softmax tiling so the
L x L score matrix never materializes in HBM); off-TPU it runs in
interpreter mode so tests cover the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite stand-in for -inf: exp() of it is exactly 0


def dot_product_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset: int = 0, k_offset: int = 0):
    """Reference attention. Shapes: q [..., Lq, H, D], k/v [..., Lk, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first query/
    key token — block-parallel callers (ring attention) pass their shard's
    global offset so causal masks line up across chips.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-3])[:, None]
        ki = k_offset + jnp.arange(k.shape[-3])[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", weights.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Pallas flash attention


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, block_k: int, n_kblocks: int, causal: bool,
                  scale: float, block_q: int):
    """One (batch*head, q-block, K-BLOCK) grid step: the key axis rides
    the grid (innermost, "arbitrary" semantics), so Mosaic's pipeline
    streams [block_k, d] K/V tiles through double-buffered VMEM DMA
    while the online-softmax state (m/l/acc) persists in VMEM scratch
    across the k steps. VMEM is O(block) — the previous design mapped
    the FULL [Lk, d] K/V into each program's VMEM, which hit the 16 MB
    scoped limit at seq 16384 (tools/diag_seq16384.log: 16.25M > 16M).

    Mosaic discipline: every ref and all scratch is kept 2-D
    ([block_q, 1] for the m/l statistics, and the SAME [block_q, 1]
    shape for the lse output block — writing it as a [1, block_q] row
    would need a sublane->lane relayout inside the kernel, a classic
    Mosaic-unsupported reshape that interpret-mode CI cannot catch)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
        k_blk = k_ref[...].astype(jnp.float32)      # [block_k, d]
        v_blk = v_ref[...].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)

    if causal:
        # A k-block strictly past this q-block's last row is fully
        # masked: skip its compute (its DMA is pipelined regardless).
        pl.when(qi * block_q + block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        # Per-row logsumexp (scores already include `scale`): persisted
        # so the backward never re-derives it with an extra pass over
        # the key blocks. Written in the statistics' native
        # [block_q, 1] layout — no cross-lane reshape inside the kernel.
        lse_ref[...] = m_scr[...] + jnp.log(l)


def _pick_block(cap: int, seq_len: int) -> int:
    """Largest ladder block <= cap that divides ``seq_len``."""
    for b in (cap, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b <= cap and b <= seq_len and seq_len % b == 0:
            return b
    return 1


def _default_blocks(seq_q: int, seq_k: int):
    """Measured tiling policy (TPU v5e block sweep, PERF.md round 5):
    256x512 won at seq 2048 (1.29x vs the old 128x128 default) and
    256x256 at seq 4096 (1.35x) — larger k-blocks amortize the online
    softmax rescale until the streamed K/V footprint presses VMEM, so
    the k-block steps down at longer key lengths. The q-block must
    divide the QUERY length and the k-block the KEY length (they differ
    for rectangular cross-attention / ring-attention shards), each
    degrading down a power-of-two ladder."""
    return (_pick_block(256, seq_q),
            _pick_block(512 if seq_k <= 2048 else 256, seq_k))


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Pallas flash attention. Shapes [B, L, H, D] -> [B, L, H, D].

    Sequence lengths must be multiples of the block sizes (pad upstream).
    Block sizes default to the measured-on-TPU policy in
    :func:`_default_blocks`; pass explicit values to override.
    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.

    Differentiable: the backward is the standard flash recurrence
    (recompute scores blockwise against the saved output, never
    materializing the [Lq, Lk] matrix) implemented with ``lax.scan`` over
    key blocks — O(Lq x block_k) live memory, XLA-fused; gradient
    exactness vs the dense reference is pinned in
    tests/test_parallel.py::TestFlashAttention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk = _default_blocks(q.shape[1], k.shape[1])
    if block_q is None:
        block_q = dq
    if block_k is None:
        block_k = dk
    return _flash(q, k, v, causal, float(scale), block_q, block_k,
                  interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return out


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (out [B, Lq, H, D], lse [B, H, Lq])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    assert Lq % block_q == 0 and Lk % block_k == 0, (Lq, Lk, block_q, block_k)

    # Collapse (B, H) into the grid's first axis; put seq minor-most for
    # contiguous VMEM tiles.
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    n_kblocks = Lk // block_k
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               n_kblocks=n_kblocks, causal=causal,
                               scale=scale, block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        # K blocks ride the grid's INNERMOST axis: sequential
        # ("arbitrary") so the scratch-carried softmax state is legal,
        # while Mosaic double-buffers the [block_k, D] K/V tile DMAs.
        grid=(B * H, Lq // block_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, qb, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb, kb: (bh, qb, 0)),
            # [block_q, 1] column per program — the statistics' native
            # layout (see the kernel's Mosaic-discipline note); the
            # trailing singleton is dropped OUTSIDE the kernel where a
            # relayout is just an XLA reshape.
            pl.BlockSpec((None, block_q, 1), lambda bh, qb, kb: (bh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, D), jnp.float32),   # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qr, kr, vr)
    return (out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3),
            lse.reshape(B, H, Lq))


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, res, do):
    """Flash backward, blockwise over key blocks (lax.scan), fp32 math.

    Standard recurrences against the forward kernel's persisted
    logsumexp:
        D_i  = rowsum(dO_i * O_i)
        P_ij = exp(S_ij - lse_i)
        dV_j = sum_i P_ij^T dO_i
        dS_ij = P_ij * (dO_i V_j^T - D_i)
        dQ_i = sum_j dS_ij K_j * scale;  dK_j = sum_i dS_ij^T Q_i * scale
    Peak live state is O(Lq x block_k) per (batch, head) — the score
    matrix is never materialized. For causal rectangular Lq < Lk, key
    blocks past the last visible key are fully masked and are skipped
    statically (the forward kernel's early-exit mirror)."""
    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bk = min(block_k, Lk)
    nkb = Lk // bk
    # Causal early-exit: keys at positions >= Lq are invisible to every
    # query row (positions both start at 0).
    nkb_live = min(nkb, -(-Lq // bk)) if causal else nkb
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    d_row = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [B, Lq, H]
    d_row = d_row.transpose(0, 2, 1)                        # [B, H, Lq]
    q_pos = jnp.arange(Lq)[:, None]

    def bwd_step(dq, jb):
        kb = jax.lax.dynamic_slice_in_dim(kf, jb * bk, bk, 1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb) * scale
        if causal:
            k_pos = jb * bk + jnp.arange(bk)[None, :]
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        vb = jax.lax.dynamic_slice_in_dim(vf, jb * bk, bk, 1)
        p = jnp.exp(s - lse[..., None])                     # [B,H,Lq,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vb)
        ds = p * (dp - d_row[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kb) * scale
        dkb = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        dvb = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        return dq, (dkb, dvb)

    dq, (dks, dvs) = jax.lax.scan(
        bwd_step, jnp.zeros(q.shape, jnp.float32), jnp.arange(nkb_live))
    # [nkb_live, B, bk, H, D] -> [B, nkb_live*bk, H, D] (+ zero tail for
    # causally-skipped key blocks).
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(B, nkb_live * bk, H, D)
    if nkb_live < nkb:
        pad = [(0, 0), (0, Lk - nkb_live * bk), (0, 0), (0, 0)]
        dk = jnp.pad(dk, pad)
        dv = jnp.pad(dv, pad)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
