"""Attention kernels: reference jnp implementation + Pallas flash attention.

These are the single-chip building blocks under the sequence-parallel
schemes in :mod:`horovod_tpu.parallel` (ring attention rotates K/V blocks
between chips and calls a block kernel locally; Ulysses reshards heads and
calls a full local kernel). The reference framework has no attention ops —
long-context support is a first-class extension of this rebuild (SURVEY
§5 "Long-context / sequence parallelism: absent").

``flash_attention`` is a Pallas TPU kernel (online-softmax tiling so the
L x L score matrix never materializes in HBM); off-TPU it runs in
interpreter mode so tests cover the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite stand-in for -inf: exp() of it is exactly 0


def dot_product_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset: int = 0, k_offset: int = 0):
    """Reference attention. Shapes: q [..., Lq, H, D], k/v [..., Lk, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first query/
    key token — block-parallel callers (ring attention) pass their shard's
    global offset so causal masks line up across chips.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-3])[:, None]
        ki = k_offset + jnp.arange(k.shape[-3])[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", weights.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Pallas flash attention


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_k: int,
                  causal: bool, scale: float, block_q: int):
    """One (batch*head, q-block) program: stream K/V blocks through VMEM
    with online softmax so only O(block_q x d) state persists."""
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
    qi = pl.program_id(1)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    n_kblocks = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    if causal:
        # Only key blocks at or before this q-block's last row contribute.
        last = (qi * block_q + block_q - 1) // block_k + 1
        n_iter = jnp.minimum(last, n_kblocks)
        m, l, acc = jax.lax.fori_loop(
            0, n_iter, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m, l, acc))

    o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Pallas flash attention. Shapes [B, L, H, D] -> [B, L, H, D].

    Sequence lengths must be multiples of the block sizes (pad upstream).
    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.
    """
    from jax.experimental import pallas as pl

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    assert Lq % block_q == 0 and Lk % block_k == 0, (Lq, Lk, block_q, block_k)

    # Collapse (B, H) into the grid's first axis; put seq minor-most for
    # contiguous VMEM tiles.
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k, seq_k=Lk,
                               causal=causal, scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Lq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, Lk, D), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, Lk, D), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
