"""Chunked fused softmax-cross-entropy: the LM lane's logits never
materialize.

A causal-LM training step at GPT-2-small scale (16k tokens/chip, vocab
32k) writes a [T, V] fp32 logits tensor of ~2 GB, reads it for
log-softmax, and touches it again on the backward — on a chip whose
step is HBM-bound, the loss head alone is ~a third of the traffic
(PERF.md). This op computes

    sum over tokens of  weight_i * -log softmax(h @ w)[target_i] / denom

by ``lax.scan`` over TOKEN chunks: each step computes one
[t_chunk, V] logits block, reduces it to per-token (logsumexp,
target-logit) immediately, and lets XLA recycle the block — peak live
logits memory is T/t_chunk times smaller, and the full tensor never
round-trips HBM. The backward recomputes each chunk's logits
(T·E·V MACs again — small next to the GBs of traffic saved on a
memory-bound step) and accumulates ``dw`` in an fp32 scan carry while
streaming ``dh`` out per chunk.

``weights``/``denom`` exist for sharded callers: a sequence-parallel
loss passes per-token validity weights and the GLOBAL (psum'd) token
count so that summing the per-shard results reproduces the dense mean
exactly (models/parallel_lm.py:next_token_nll_fused).
``tp_vocab_cross_entropy`` is the Megatron-style variant for a head
sharded [E, V/tp] over a mesh axis.

The reference framework has no fused loss (its LM story is absent
altogether — SURVEY §5 long-context); this is TPU-first perf work in
the spirit of its fusion buffer: restructure the computation so the
interconnect — here HBM — moves as few bytes as the math allows.

Exactness (loss AND both gradients) vs the dense composition is pinned
in tests/test_xent.py; ``bench.py --fused-ce`` A/Bs it at protocol
scale.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pad_all(h, targets, weights, t_chunk):
    """Pad the token axis to a multiple of t_chunk; padded rows carry
    weight 0 and target 0 (any valid index)."""
    t = h.shape[0]
    pad = (-t) % t_chunk
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        weights = jnp.pad(weights, (0, pad))
    return h, targets, weights


def _fill_defaults(h, weights, denom):
    if weights is None:
        weights = jnp.ones((h.shape[0],), jnp.float32)
    else:
        weights = weights.astype(jnp.float32)
    if denom is None:
        denom = jnp.sum(weights)
    return weights, jnp.asarray(denom, jnp.float32)


def _chunk_stats(hc, w, tc):
    """One chunk's per-token (lse, target_logit), fp32."""
    logits = jnp.dot(hc, w, preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
    return lse, tgt


def fused_cross_entropy(h, w, targets, t_chunk: int = 512,
                        weights=None, denom=None):
    """Weighted NLL without materializing [T, V] logits.

    h [T, E] (any float dtype; the matmul accumulates fp32), w [E, V],
    targets [T] int32 -> scalar fp32. Defaults (weights=1, denom=T)
    give the plain mean NLL; sharded callers pass validity weights and
    a globally-reduced denom (module docstring).

    ``weights`` and ``denom`` are NON-DIFFERENTIABLE bookkeeping
    (validity masks, token counts): they are passed through
    ``stop_gradient`` at entry, so differentiating w.r.t. a learnable
    per-token weighting yields zeros by contract, not by accident. Use
    an explicit elementwise product outside this op if you need
    gradients through a weighting.
    """
    weights, denom = _fill_defaults(h, weights, denom)
    weights = lax.stop_gradient(weights)
    denom = lax.stop_gradient(denom)
    return _fce(h, w, targets, weights, denom, t_chunk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fce(h, w, targets, weights, denom, t_chunk):
    loss, _ = _fce_fwd(h, w, targets, weights, denom, t_chunk)
    return loss


def _chunked(h, targets, weights, t_chunk):
    hp, tp_, wp = _pad_all(h, targets, weights, t_chunk)
    n = hp.shape[0] // t_chunk
    return (hp.reshape(n, t_chunk, h.shape[1]), tp_.reshape(n, t_chunk),
            wp.reshape(n, t_chunk))


def _fce_fwd(h, w, targets, weights, denom, t_chunk):
    from horovod_tpu.parallel._vma import match_vma

    hcs, tcs, wcs = _chunked(h, targets, weights, t_chunk)

    def step(acc, xs):
        hc, tc, wc = xs
        lse, tgt = _chunk_stats(hc, w, tc)
        return acc + jnp.sum((lse - tgt) * wc), None

    # Scan carries must be vma-typed like the body's output (e.g. a
    # sequence-parallel caller passes sp-varying h/targets/weights).
    acc0 = match_vma(jnp.float32(0.0), h, w, targets, weights)
    total, _ = lax.scan(step, acc0, (hcs, tcs, wcs))
    return total / denom, (h, w, targets, weights, denom)


def _fce_bwd(t_chunk, res, g):
    from horovod_tpu.parallel._vma import match_vma

    h, w, targets, weights, denom = res
    hcs, tcs, wcs = _chunked(h, targets, weights, t_chunk)
    e = h.shape[1]
    scale = g / denom

    def step(dw_acc, xs):
        hc, tc, wc = xs
        logits = jnp.dot(hc, w, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(tc, w.shape[1], dtype=jnp.float32)
        dl = (p - onehot) * (wc * scale)[:, None]  # [t_chunk, V] fp32
        dh_c = jnp.dot(dl, w.T.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        dw_acc = dw_acc + jnp.dot(hc.astype(jnp.float32).T, dl,
                                  preferred_element_type=jnp.float32)
        return dw_acc, dh_c

    dw0 = match_vma(jnp.zeros(w.shape, jnp.float32),
                    h, w, targets, weights, denom, g)
    dw, dhs = lax.scan(step, dw0, (hcs, tcs, wcs))
    dh = dhs.reshape(-1, e)[:h.shape[0]]
    # weights/denom carry data-independent bookkeeping (validity masks,
    # token counts): their true gradients are not needed by any caller.
    return (dh.astype(h.dtype), dw.astype(w.dtype), None,
            jnp.zeros_like(weights), jnp.zeros_like(denom))


_fce.defvjp(_fce_fwd, _fce_bwd)


# --------------------------------------------------------------------------
# Vocab-parallel (tensor-parallel head) variant.


def _vp_chunk_stats(hc, w_local, tc, axis, v_local, descale_grads=False):
    """One chunk's per-token (global lse, global target logit) when the
    vocab axis is sharded over mesh axis ``axis``. ``descale_grads``
    is the plain-autodiff path's psum-transpose correction
    (:func:`_descale_grad`); the custom VJP never differentiates
    through here and leaves it off."""
    logits = jnp.dot(hc, w_local, preferred_element_type=jnp.float32)
    if descale_grads:
        logits = _descale_grad(logits, axis)
    # stop_gradient on the stabilizer is EXACT (the log-sum-exp max
    # shift's gradient contributions cancel identically) and lets the
    # legacy plain-autodiff path (_vp_plain) differentiate through this
    # function — pmax has no differentiation rule on 0.4.x runtimes.
    gmax = lax.stop_gradient(
        lax.pmax(jnp.max(lax.stop_gradient(logits), axis=-1), axis))
    lse = gmax + jnp.log(lax.psum(
        jnp.sum(jnp.exp(logits - gmax[:, None]), axis=-1), axis))
    offset = lax.axis_index(axis) * v_local
    local_t = tc - offset
    in_range = (local_t >= 0) & (local_t < v_local)
    picked = jnp.take_along_axis(
        logits, jnp.clip(local_t, 0, v_local - 1)[:, None], axis=-1)[:, 0]
    tgt = lax.psum(jnp.where(in_range, picked, 0.0), axis)
    return lse, tgt


def tp_vocab_cross_entropy(h, w_local, targets, axis: str,
                           t_chunk: int = 512, weights=None, denom=None):
    """Megatron-style vocab-parallel CE, chunked — for use INSIDE
    ``shard_map`` where the projection weight is sharded [E, V/tp] over
    mesh axis ``axis`` and ``h``/``targets`` are replicated along it.

    Each rank computes its local [t_chunk, V/tp] logits block; the
    softmax normalizer is assembled with a pmax + psum per chunk (two
    scalars-per-token on the ICI instead of a V-wide all-gather), the
    target logit with a masked psum. Returns the GLOBAL weighted NLL —
    identical on every ``axis`` rank, exactly equal to the dense
    computation (pinned in tests/test_xent.py). The custom VJP
    recomputes blockwise: dw stays rank-local (exactly the dense dw's
    vocab slice), dh is psum-assembled across the shards.

    As with :func:`fused_cross_entropy`, ``weights``/``denom`` are
    non-differentiable bookkeeping and are ``stop_gradient``-ed at
    entry — a learnable weighting must be applied outside this op.
    """
    from horovod_tpu.parallel._vma import vma_typing_available

    weights, denom = _fill_defaults(h, weights, denom)
    weights = lax.stop_gradient(weights)
    denom = lax.stop_gradient(denom)
    if not vma_typing_available():
        # Legacy (check_rep-era) runtimes cannot run the custom-VJP
        # spelling: the old scan checker rejects the psum-collapsed
        # carry type ("mismatched replication types" — lax.pcast
        # polyfills to identity, so the carry can never be typed), and
        # the shard_map TRANSPOSE machinery dies on the VJP's rank-0
        # residuals (_SpecError on float32[]; rank-0 values have no dim
        # to carry the stacking axis names). Fall back to the SAME
        # chunk math, unrolled, under plain autodiff — numerically
        # identical loss/grads (pinned vs dense in tests/test_xent.py)
        # at the cost of autodiff saving per-chunk logits, i.e. the
        # op's HBM win is traded for correctness on runtimes that
        # cannot express it. The 3-test tier-1 class this closes was
        # carried since PR 1.
        return _vp_plain(h, w_local, targets, weights, denom, axis,
                         t_chunk)
    return _vp(h, w_local, targets, weights, denom, axis, t_chunk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _descale_grad(x, axis):
    """Identity whose backward divides by the ``axis`` size.

    Every path from the local logits to :func:`_vp_plain`'s loss crosses
    exactly one raw ``lax.psum`` (the lse normalizer or the masked
    target pick), and a raw psum's transpose is psum — the classic
    gotcha (see parallel/tp.py:tp_region_output) that scales the
    cotangent by the axis size while keeping only this rank's shard
    term. Dividing here restores the exact per-rank dl the custom VJP
    computes, so dw_local comes out as the dense dw's vocab slice."""
    return x


def _descale_fwd(x, axis):
    return x, None


def _descale_bwd(axis, _, g):
    return (g / lax.axis_size(axis),)


_descale_grad.defvjp(_descale_fwd, _descale_bwd)


def _vp_plain(h, w_local, targets, weights, denom, axis, t_chunk):
    """The vocab-parallel CE as a plain (non-custom-VJP) unrolled chunk
    loop — the legacy-runtime fallback of :func:`tp_vocab_cross_entropy`.

    Two conjugates make IN-REGION autodiff (a ``jax.grad`` taken inside
    the shard_map body — the training path, models/parallel_lm.py)
    reproduce the custom VJP's gradient conventions exactly:
    :func:`_descale_grad` on the local logits undoes the psum-transposed
    cotangent's axis-size scaling (leaving dw rank-local, the dense
    slice), and ``tp_region_input`` on ``h`` assembles dh across the
    vocab shards (each rank's backward only carries its own slice's
    term; the true dh is their sum). Rank-1 accumulator on purpose: a
    rank-0 axis-varying value is exactly what the old rewrite machinery
    cannot name.

    Known legacy limitation: differentiating THROUGH the shard_map
    boundary (``jax.grad`` outside the region) double-corrects —
    the boundary transpose is already exact there, and without vma
    typing the op cannot mark its assembled cotangents as invariant,
    so ``dw`` comes out axis-size-times small at a legacy boundary.
    Modern runtimes reconcile both conventions through vma typing
    (``_vp``'s typed residuals); legacy cannot express it, so the
    through-boundary grad pins are version-gated xfails in
    tests/test_xent.py while the in-region pins (the convention every
    in-repo caller uses) hold on every runtime."""
    from horovod_tpu.parallel.tp import tp_region_input

    h = tp_region_input(h, axis)
    hcs, tcs, wcs = _chunked(h, targets, weights, t_chunk)
    v_local = w_local.shape[1]
    total = jnp.zeros((1,), jnp.float32)
    for i in range(hcs.shape[0]):
        lse, tgt = _vp_chunk_stats(hcs[i], w_local, tcs[i], axis, v_local,
                                   descale_grads=True)
        total = total + jnp.sum((lse - tgt) * wcs[i]).reshape(1)
    return (total / denom)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _vp(h, w_local, targets, weights, denom, axis, t_chunk):
    loss, _ = _vp_fwd(h, w_local, targets, weights, denom, axis, t_chunk)
    return loss


def _vp_body_vma(axis, *with_axis_removed, extra=()):
    """vma set of a scan-body output whose ``axis``-variance was
    collapsed by the in-body psum/pmax, unioned with operands that
    touch the result after the collectives."""
    from horovod_tpu.parallel._vma import vma_of

    return ((vma_of(*with_axis_removed) - {axis}) | vma_of(*extra))


def _typed_zero(shape_like, vma):
    z = (jnp.float32(0.0) if shape_like is None
         else jnp.zeros(shape_like.shape, jnp.float32))
    if vma:
        z = lax.pcast(z, tuple(sorted(vma)), to="varying")
    return z


def _vp_fwd(h, w_local, targets, weights, denom, axis, t_chunk):
    hcs, tcs, wcs = _chunked(h, targets, weights, t_chunk)
    v_local = w_local.shape[1]

    def step(acc, xs):
        hc, tc, wc = xs
        lse, tgt = _vp_chunk_stats(hc, w_local, tc, axis, v_local)
        return acc + jnp.sum((lse - tgt) * wc), None

    # (lse, tgt) come out of psum/pmax over ``axis`` — axis-invariant —
    # but keep any OTHER variance (e.g. sp) the operands carry.
    acc0 = _typed_zero(None, _vp_body_vma(axis, h, w_local,
                                          extra=(targets, weights)))
    total, _ = lax.scan(step, acc0, (hcs, tcs, wcs))
    return total / denom, (h, w_local, targets, weights, denom)


def _vp_bwd(axis, t_chunk, res, g):
    h, w_local, targets, weights, denom = res
    hcs, tcs, wcs = _chunked(h, targets, weights, t_chunk)
    e = h.shape[1]
    v_local = w_local.shape[1]
    scale = g / denom

    def step(dw_acc, xs):
        hc, tc, wc = xs
        logits = jnp.dot(hc, w_local, preferred_element_type=jnp.float32)
        lse, _ = _vp_chunk_stats(hc, w_local, tc, axis, v_local)
        p = jnp.exp(logits - lse[:, None])  # local slice of the softmax
        offset = lax.axis_index(axis) * v_local
        local_t = tc - offset
        in_range = (local_t >= 0) & (local_t < v_local)
        onehot = jax.nn.one_hot(jnp.clip(local_t, 0, v_local - 1),
                                v_local, dtype=jnp.float32)
        onehot = onehot * in_range[:, None].astype(jnp.float32)
        dl = (p - onehot) * (wc * scale)[:, None]
        # h is axis-replicated, logits axis-split: dh sums the shards.
        dh_c = lax.psum(
            jnp.dot(dl, w_local.T.astype(jnp.float32),
                    preferred_element_type=jnp.float32), axis)
        dw_acc = dw_acc + jnp.dot(hc.astype(jnp.float32).T, dl,
                                  preferred_element_type=jnp.float32)
        return dw_acc, dh_c

    # The accumulator is axis-varying (each rank owns its vocab slice
    # of dw) on top of whatever variance (e.g. sp) the operands carry.
    from horovod_tpu.parallel._vma import vma_of

    dw0 = _typed_zero(w_local, vma_of(h, w_local, targets, weights,
                                      denom, g) | {axis})
    dw, dhs = lax.scan(step, dw0, (hcs, tcs, wcs))
    dh = dhs.reshape(-1, e)[:h.shape[0]]
    return (dh.astype(h.dtype), dw.astype(w_local.dtype), None,
            jnp.zeros_like(weights), jnp.zeros_like(denom))


_vp.defvjp(_vp_fwd, _vp_bwd)
