"""Chunked fused softmax-cross-entropy: the LM lane's logits never
materialize.

A causal-LM training step at GPT-2-small scale (16k tokens/chip, vocab
32k) writes a [T, V] fp32 logits tensor of ~2 GB, reads it for
log-softmax, and touches it again on the backward — on a chip whose
step is HBM-bound, the loss head alone is ~a third of the traffic
(PERF.md). This op computes

    mean over tokens of  -log softmax(h @ w)[target]

by ``lax.scan`` over TOKEN chunks: each step computes one
[t_chunk, V] logits block, reduces it to per-token (logsumexp,
target-logit) immediately, and lets XLA recycle the block — peak live
logits memory is T/t_chunk times smaller, and the full tensor never
round-trips HBM. The backward recomputes each chunk's logits
(T·E·V MACs again — small next to the GBs of traffic saved on a
memory-bound step) and accumulates ``dw`` in an fp32 scan carry while
streaming ``dh`` out per chunk.

The reference framework has no fused loss (its LM story is absent
altogether — SURVEY §5 long-context); this is TPU-first perf work in
the spirit of its fusion buffer: restructure the computation so the
interconnect — here HBM — moves as few bytes as the math allows.

Exactness (loss AND both gradients) vs the dense composition is pinned
in tests/test_xent.py; ``bench.py --fused-ce`` A/Bs it at protocol
scale.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _pad_tokens(h, targets, t_chunk):
    """Pad the token axis to a multiple of t_chunk; padded rows carry
    weight 0 and target 0 (any valid index)."""
    t = h.shape[0]
    pad = (-t) % t_chunk
    weights = jnp.ones((t,), jnp.float32)
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        weights = jnp.pad(weights, (0, pad))
    return h, targets, weights, t


def _chunk_stats(hc, w, tc):
    """One chunk's per-token (lse, target_logit), fp32."""
    logits = jnp.dot(hc, w, preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
    return lse, tgt


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(h, w, targets, t_chunk: int = 512):
    """Mean negative log-likelihood without materializing [T, V] logits.

    h [T, E] (any float dtype; the matmul accumulates fp32),
    w [E, V], targets [T] int32 -> scalar fp32 mean NLL over T tokens.
    """
    loss, _ = _fce_fwd(h, w, targets, t_chunk)
    return loss


def _fce_fwd(h, w, targets, t_chunk):
    hp, tp, weights, t = _pad_tokens(h, targets, t_chunk)
    n = hp.shape[0] // t_chunk
    hcs = hp.reshape(n, t_chunk, h.shape[1])
    tcs = tp.reshape(n, t_chunk)
    wcs = weights.reshape(n, t_chunk)

    def step(acc, xs):
        hc, tc, wc = xs
        lse, tgt = _chunk_stats(hc, w, tc)
        return acc + jnp.sum((lse - tgt) * wc), None

    total, _ = lax.scan(step, jnp.float32(0.0), (hcs, tcs, wcs))
    return total / t, (h, w, targets)


def _fce_bwd(t_chunk, res, g):
    h, w, targets = res
    hp, tp, weights, t = _pad_tokens(h, targets, t_chunk)
    n = hp.shape[0] // t_chunk
    e = h.shape[1]
    hcs = hp.reshape(n, t_chunk, e)
    tcs = tp.reshape(n, t_chunk)
    wcs = weights.reshape(n, t_chunk)
    scale = g / t  # d(mean)/d(per-token nll), folded in fp32

    def step(dw_acc, xs):
        hc, tc, wc = xs
        logits = jnp.dot(hc, w, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(tc, w.shape[1], dtype=jnp.float32)
        dl = (p - onehot) * (wc * scale)[:, None]  # [t_chunk, V] fp32
        dh_c = jnp.dot(dl, w.T.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        dw_acc = dw_acc + jnp.dot(hc.astype(jnp.float32).T, dl,
                                  preferred_element_type=jnp.float32)
        return dw_acc, dh_c

    dw, dhs = lax.scan(step, jnp.zeros(w.shape, jnp.float32),
                       (hcs, tcs, wcs))
    dh = dhs.reshape(n * t_chunk, e)[:h.shape[0]]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


fused_cross_entropy.defvjp(_fce_fwd, _fce_bwd)


# --------------------------------------------------------------------------
# Vocab-parallel (tensor-parallel head) variant.


def _vp_chunk_stats(hc, w_local, tc, axis, v_local):
    """One chunk's per-token (global lse, global target logit) when the
    vocab axis is sharded over mesh axis ``axis``."""
    logits = jnp.dot(hc, w_local, preferred_element_type=jnp.float32)
    gmax = lax.pmax(jnp.max(logits, axis=-1), axis)
    lse = gmax + jnp.log(lax.psum(
        jnp.sum(jnp.exp(logits - gmax[:, None]), axis=-1), axis))
    offset = lax.axis_index(axis) * v_local
    local_t = tc - offset
    in_range = (local_t >= 0) & (local_t < v_local)
    picked = jnp.take_along_axis(
        logits, jnp.clip(local_t, 0, v_local - 1)[:, None], axis=-1)[:, 0]
    tgt = lax.psum(jnp.where(in_range, picked, 0.0), axis)
    return lse, tgt


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def tp_vocab_cross_entropy(h, w_local, targets, axis: str,
                           t_chunk: int = 512):
    """Megatron-style vocab-parallel CE, chunked — for use INSIDE
    ``shard_map`` where the projection weight is sharded [E, V/tp] over
    mesh axis ``axis`` and ``h``/``targets`` are replicated along it.

    Each rank computes its local [t_chunk, V/tp] logits block; the
    softmax normalizer is assembled with a pmax + psum per chunk (two
    scalars-per-token on the ICI instead of a V-wide all-gather), the
    target logit with a masked psum. Returns the GLOBAL mean NLL —
    identical on every ``axis`` rank, exactly equal to the dense
    computation (pinned in tests/test_xent.py). The custom VJP
    recomputes blockwise: dw stays rank-local (exactly the dense dw's
    vocab slice), dh is psum-assembled across the shards.
    """
    loss, _ = _vp_fwd(h, w_local, targets, axis, t_chunk)
    return loss


def _vp_fwd(h, w_local, targets, axis, t_chunk):
    hp, tp_, weights, t = _pad_tokens(h, targets, t_chunk)
    n = hp.shape[0] // t_chunk
    v_local = w_local.shape[1]
    hcs = hp.reshape(n, t_chunk, h.shape[1])
    tcs = tp_.reshape(n, t_chunk)
    wcs = weights.reshape(n, t_chunk)

    def step(acc, xs):
        hc, tc, wc = xs
        lse, tgt = _vp_chunk_stats(hc, w_local, tc, axis, v_local)
        return acc + jnp.sum((lse - tgt) * wc), None

    total, _ = lax.scan(step, jnp.float32(0.0), (hcs, tcs, wcs))
    return total / t, (h, w_local, targets)


def _vp_bwd(axis, t_chunk, res, g):
    h, w_local, targets = res
    hp, tp_, weights, t = _pad_tokens(h, targets, t_chunk)
    n = hp.shape[0] // t_chunk
    e = h.shape[1]
    v_local = w_local.shape[1]
    hcs = hp.reshape(n, t_chunk, e)
    tcs = tp_.reshape(n, t_chunk)
    wcs = weights.reshape(n, t_chunk)
    scale = g / t

    def step(dw_acc, xs):
        hc, tc, wc = xs
        logits = jnp.dot(hc, w_local, preferred_element_type=jnp.float32)
        lse, _ = _vp_chunk_stats(hc, w_local, tc, axis, v_local)
        p = jnp.exp(logits - lse[:, None])  # local slice of the softmax
        offset = lax.axis_index(axis) * v_local
        local_t = tc - offset
        in_range = (local_t >= 0) & (local_t < v_local)
        onehot = jax.nn.one_hot(jnp.clip(local_t, 0, v_local - 1),
                                v_local, dtype=jnp.float32)
        onehot = onehot * in_range[:, None].astype(jnp.float32)
        dl = (p - onehot) * (wc * scale)[:, None]
        # h is axis-replicated, logits axis-split: dh sums the shards.
        dh_c = lax.psum(
            jnp.dot(dl, w_local.T.astype(jnp.float32),
                    preferred_element_type=jnp.float32), axis)
        dw_acc = dw_acc + jnp.dot(hc.astype(jnp.float32).T, dl,
                                  preferred_element_type=jnp.float32)
        return dw_acc, dh_c

    # The accumulator is tp-varying (each rank owns its vocab slice of
    # dw) — the initial zeros must carry the same vma type.
    dw0 = lax.pcast(jnp.zeros(w_local.shape, jnp.float32), (axis,),
                    to="varying")
    dw, dhs = lax.scan(step, dw0, (hcs, tcs, wcs))
    dh = dhs.reshape(n * t_chunk, e)[:h.shape[0]]
    return dh.astype(h.dtype), dw.astype(w_local.dtype), None


tp_vocab_cross_entropy.defvjp(_vp_fwd, _vp_bwd)
