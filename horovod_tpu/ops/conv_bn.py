"""Fused 1x1-conv (matmul) + BatchNorm-statistics Pallas kernel.

The ResNet-class benchmark step is memory-bound: profiling (PERF.md) puts
~34% of device time in ``convert_reduce`` fusions — the bf16->f32
converts feeding the BatchNorm statistics reductions. The forward half of
that cost is a full HBM re-read of every conv output just to compute its
channel mean/variance. ResNet-50's bottleneck blocks make 36 of its 53
convolutions 1x1 — i.e. plain matmuls on the MXU — so this kernel folds
the statistics into the matmul epilogue: while each output tile is still
in VMEM it accumulates per-channel ``sum(y)`` and ``sum(y^2)`` into a
grid-resident accumulator, eliminating the separate statistics pass over
~0.9 GB of activations per forward step.

Phase 2 (prologue fusion): the bottleneck's 3x3 output is consumed ONLY
by the following 1x1, so that producer's BatchNorm apply + ReLU can run
in this matmul's PROLOGUE while the raw tile is in VMEM — the
normalized activation ``h = relu(x*a + b)`` never reaches HBM at all
(one more full write + read of a [B,H,W,F] tensor saved per block).
Both phases share ONE kernel/forward, parameterized by the optional
``(a, b)`` affine.

The reference framework has no counterpart op (its benchmark model was
stock torchvision ResNet-50, reference
examples/pytorch_synthetic_benchmark.py:24-35); this is TPU-first perf
work on the same workload, not a port.

Gradient story (exact, not approximate): the public ops return
``(y, s1, s2)`` and the BN apply of THIS layer happens outside in
regular jnp, so autodiff needs the VJP of ``inputs -> (y, s1, s2)``
where ``s1 = sum_rows(cast(y)), s2 = sum_rows(cast(y)^2)``. With
incoming cotangents ``(dy, ds1, ds2)`` the chain rule collapses to a
single per-element total

    dy_total = dy + ds1[c] + 2 * y[r, c] * ds2[c]

followed by the standard matmul gradients (and, for the prologue
variant, the elementwise affine/ReLU pullbacks, with ``h`` recomputed
from the raw input — the same bytes the unfused backward reads from the
stored activation). Exactness vs the unfused compositions is pinned in
tests/test_conv_bn.py, f64-tight.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Keep the whole [K, N] weight + one [block_m, K] input tile + the f32
# accumulator resident in VMEM; fall back to the unfused path when the
# estimate exceeds this budget (v4/v5 VMEM is 16 MB; leave headroom for
# Mosaic's own buffers).
_VMEM_BUDGET_BYTES = 13 * 1024 * 1024

_BLOCK_M_CANDIDATES = (512, 448, 256, 128, 64, 32, 16, 8)


def _pick_block_m(m: int) -> Optional[int]:
    for bm in _BLOCK_M_CANDIDATES:
        if m % bm == 0:
            return bm
    return None


def fits_fused(m: int, k: int, n: int, itemsize: int = 2) -> bool:
    """Whether the fused kernel's working set fits the VMEM budget."""
    bm = _pick_block_m(m) or 256
    weight = k * n * itemsize
    x_tile = bm * k * itemsize
    y_tile = bm * n * itemsize
    acc = bm * n * 4
    return weight + x_tile + y_tile + acc <= _VMEM_BUDGET_BYTES


def _make_kernel(prologue: bool, valid_rows: Optional[int], bm: int):
    """Kernel for one M-tile: optional affine+ReLU prologue, matmul on
    the MXU, statistics in the epilogue.

    s1/s2 use a constant index map, so their [1, N] block stays resident
    in VMEM across the whole (sequential) grid — the classic Pallas
    reduction-accumulator pattern. ``valid_rows`` (set only when M was
    zero-padded to a block multiple AND a prologue runs) masks the pad
    rows back to zero AFTER the affine — relu(0*a + b) = relu(b) is
    nonzero for positive shifts and would otherwise poison the
    statistics; without a prologue, zero rows stay zero on their own.
    """

    def kernel(*refs):
        from jax.experimental import pallas as pl

        if prologue:
            x_ref, a_ref, b_ref, w_ref, y_ref, s1_ref, s2_ref = refs
        else:
            x_ref, w_ref, y_ref, s1_ref, s2_ref = refs
        i = pl.program_id(0)
        xb = x_ref[...]
        if prologue:
            # The affine runs in the storage dtype (bf16 on TPU),
            # matching the unfused ConvBN apply channel-for-channel.
            xb = jnp.maximum(xb * a_ref[...] + b_ref[...], 0)
            if valid_rows is not None:
                row = i * bm + jax.lax.broadcasted_iota(
                    jnp.int32, xb.shape, 0)
                xb = jnp.where(row < valid_rows, xb, 0)
        # f32 MXU accumulation for <=32-bit inputs; f64 only exists for
        # the float64 exactness probes in CI (TPUs have no f64 path).
        acc_t = (jnp.float64 if xb.dtype == jnp.float64 else jnp.float32)
        acc = jnp.dot(xb, w_ref[...], preferred_element_type=acc_t)
        y_ref[...] = acc.astype(y_ref.dtype)
        # Statistics over the ROUNDED output (what the unfused path sees
        # when it upcasts the stored bf16 activation), so fused and
        # unfused BN consume identical moments.
        yr = y_ref[...].astype(s1_ref.dtype)
        ps1 = jnp.sum(yr, axis=0, keepdims=True)
        ps2 = jnp.sum(yr * yr, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _init():
            s1_ref[...] = ps1
            s2_ref[...] = ps2

        @pl.when(i > 0)
        def _accum():
            s1_ref[...] += ps1
            s2_ref[...] += ps2

    return kernel


def _vma_align(*arrays):
    """pcast every array onto the union of all arrays' varying mesh
    axes (shard_map check_vma=True requires dot/elementwise operands to
    agree; a replicated weight meeting batch-sharded activations needs
    the explicit cast). Returns (aligned_arrays, union)."""
    vmas = []
    for arr in arrays:
        try:
            vmas.append(jax.typeof(arr).vma)
        except (AttributeError, TypeError):
            vmas.append(frozenset())
    union = frozenset().union(*vmas)
    out = []
    for arr, vma in zip(arrays, vmas):
        missing = union - vma
        if missing:
            arr = jax.lax.pcast(arr, tuple(missing), to="varying")
        out.append(arr)
    return out, union


def _forward(x, w, a, b, interpret: bool):
    """x [M, K] (raw if a/b given), w [K, N], optional affine a/b [K] ->
    (y [M, N] x.dtype, s1 [N], s2 [N])."""
    from jax.experimental import pallas as pl

    prologue = a is not None
    m, k = x.shape
    n = w.shape[1]
    # Stats accumulate in f32 (f64 only under the CI exactness probes).
    stats_t = jnp.promote_types(jnp.float32, x.dtype)
    bm = _pick_block_m(m)
    pad = 0
    if bm is None:
        # Irregular row counts: zero rows contribute nothing to s1/s2
        # (the kernel masks them back to zero when a prologue runs) and
        # their y rows are sliced off below.
        bm = 256
        pad = (-m) % bm
        x = jnp.pad(x, ((0, pad), (0, 0)))

    operands = [x, w]
    in_specs = [
        pl.BlockSpec((bm, k), lambda i: (i, 0)),
        pl.BlockSpec((k, n), lambda i: (0, 0)),
    ]
    if prologue:
        a2 = a.reshape(1, k).astype(x.dtype)
        b2 = b.reshape(1, k).astype(x.dtype)
        operands = [x, a2, b2, w]
        in_specs = [
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
        ]
    operands, vma = _vma_align(*operands)

    def out_struct(shape, dtype):
        # Legacy jax (check_rep era) has no vma kwarg on ShapeDtypeStruct
        # — and no vma typing at all, so _vma_align always returns the
        # empty set there and plain structs are exactly right. Passing
        # the kwarg only when a nonempty set needs expressing keeps one
        # code path valid on both runtimes (same compat discipline as
        # common/jax_compat.py).
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    kernel = _make_kernel(prologue, m if (prologue and pad) else None, bm)
    y, s1, s2 = pl.pallas_call(
        kernel,
        grid=((m + pad) // bm,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            out_struct((m + pad, n), x.dtype),
            out_struct((1, n), stats_t),
            out_struct((1, n), stats_t),
        ],
        interpret=interpret,
    )(*operands)
    if pad:
        y = y[:m]
    return y, s1[0], s2[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_bn_stats(x, w, interpret: bool = False):
    """Fused ``y = x @ w`` plus channel statistics ``(sum y, sum y^2)``.

    The statistics are computed over the rounded (storage-dtype) ``y`` in
    one pass while each tile is VMEM-resident. ``interpret=True`` runs
    the same kernel through the Pallas interpreter (CPU CI).
    """
    return _forward(x, w, None, None, interpret)


def _matmul_bn_stats_fwd(x, w, interpret):
    y, s1, s2 = _forward(x, w, None, None, interpret)
    return (y, s1, s2), (x, w, y)


def _stats_cotangent_total(y, dy, ds1, ds2, acc_t):
    """Collapse the three cotangent paths into one elementwise total
    (module docstring); XLA fuses the broadcasts + add with the matmul
    operand preparation."""
    return (dy.astype(acc_t)
            + ds1[None, :].astype(acc_t)
            + 2.0 * y.astype(acc_t) * ds2[None, :].astype(acc_t))


def _matmul_bn_stats_bwd(interpret, res, cts):
    x, w, y = res
    dy, ds1, ds2 = cts
    acc_t = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
    dy_total = _stats_cotangent_total(y, dy, ds1, ds2, acc_t).astype(x.dtype)
    dx = jnp.dot(dy_total, w.T, preferred_element_type=acc_t)
    dw = jnp.dot(x.T, dy_total, preferred_element_type=acc_t)
    return dx.astype(x.dtype), dw.astype(w.dtype)


matmul_bn_stats.defvjp(_matmul_bn_stats_fwd, _matmul_bn_stats_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def matmul_prologue_bn_stats(x, a, b, w, interpret: bool = False):
    """Fused ``y = relu(x*a + b) @ w`` plus channel statistics of ``y``.

    ``x`` is the RAW previous-conv output; ``a``/``b`` the folded
    BatchNorm scale/shift of that previous layer. The normalized
    activation exists only tile-by-tile in VMEM.
    """
    return _forward(x, w, a, b, interpret)


def _matmul_prologue_fwd(x, a, b, w, interpret):
    y, s1, s2 = _forward(x, w, a, b, interpret)
    return (y, s1, s2), (x, a, b, w, y)


def _matmul_prologue_bwd(interpret, res, cts):
    x, a, b, w, y = res
    dy, ds1, ds2 = cts
    acc_t = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
    dy_total = _stats_cotangent_total(y, dy, ds1, ds2, acc_t).astype(x.dtype)
    # Recompute h elementwise from the raw input (one read of x — the
    # same bytes the unfused backward reads from the STORED h, so the
    # backward pays no extra HBM traffic for never materializing h).
    pre = x * a[None, :].astype(x.dtype) + b[None, :].astype(x.dtype)
    h = jnp.maximum(pre, 0)
    mask = (pre > 0).astype(x.dtype)
    dw = jnp.dot(h.T, dy_total, preferred_element_type=acc_t)
    dh = jnp.dot(dy_total, w.T, preferred_element_type=acc_t).astype(x.dtype)
    dh = dh * mask
    dx = dh * a[None, :].astype(x.dtype)
    da = jnp.sum(dh.astype(acc_t) * x.astype(acc_t), axis=0)
    db = jnp.sum(dh.astype(acc_t), axis=0)
    return (dx.astype(x.dtype), da.astype(a.dtype), db.astype(b.dtype),
            dw.astype(w.dtype))


matmul_prologue_bn_stats.defvjp(_matmul_prologue_fwd, _matmul_prologue_bwd)


def _nhwc_wrap(op, x, w, strides, interpret, *affine):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if w.ndim == 4:
        assert w.shape[:2] == (1, 1), w.shape
        w = w[0, 0]
    sh, sw = strides
    if (sh, sw) != (1, 1):
        # A strided 1x1 conv only ever reads the stride-subsampled
        # input: the same matmul over x[:, ::sh, ::sw] — a strided HBM
        # read of 1/(sh*sw) of the data, not an extra pass. (With a
        # prologue the subsample commutes with the elementwise affine.)
        x = x[:, ::sh, ::sw, :]
    bsz, hh, ww_, c = x.shape
    y, s1, s2 = op(x.reshape(bsz * hh * ww_, c), *affine, w, interpret)
    return y.reshape(bsz, hh, ww_, -1), s1, s2


def conv1x1_bn_stats(x, w, strides: Tuple[int, int] = (1, 1),
                     interpret: Optional[bool] = None):
    """1x1 NHWC convolution with fused BN statistics.

    x [B, H, W, C_in], w [1, 1, C_in, C_out] (or [C_in, C_out]) ->
    (y [B, H', W', C_out], s1 [C_out], s2 [C_out]).
    """
    return _nhwc_wrap(matmul_bn_stats, x, w, strides, interpret)


def conv1x1_prologue_bn_stats(x, a, b, w,
                              strides: Tuple[int, int] = (1, 1),
                              interpret: Optional[bool] = None):
    """NHWC wrapper of :func:`matmul_prologue_bn_stats`: ``x`` is the
    RAW producing-conv output, ``a``/``b`` its folded BN scale/shift."""
    return _nhwc_wrap(matmul_prologue_bn_stats, x, w, strides, interpret,
                      a, b)
