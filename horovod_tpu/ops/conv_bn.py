"""Fused 1x1-conv (matmul) + BatchNorm-statistics Pallas kernel.

The ResNet-class benchmark step is memory-bound: profiling (PERF.md) puts
~34% of device time in ``convert_reduce`` fusions — the bf16->f32
converts feeding the BatchNorm statistics reductions. The forward half of
that cost is a full HBM re-read of every conv output just to compute its
channel mean/variance. ResNet-50's bottleneck blocks make 36 of its 53
convolutions 1x1 — i.e. plain matmuls on the MXU — so this kernel folds
the statistics into the matmul epilogue: while each output tile is still
in VMEM it accumulates per-channel ``sum(y)`` and ``sum(y^2)`` into a
grid-resident accumulator, eliminating the separate statistics pass over
~0.9 GB of activations per forward step.

The reference framework has no counterpart op (its benchmark model was
stock torchvision ResNet-50, reference
examples/pytorch_synthetic_benchmark.py:24-35); this is TPU-first perf
work on the same workload, not a port.

Gradient story (exact, not approximate): the public op returns
``(y, s1, s2)`` and the BN apply happens outside in regular jnp, so
autodiff needs the VJP of the map ``x, w -> (y, s1, s2)`` where
``s1 = sum_rows(cast(y)), s2 = sum_rows(cast(y)^2)``. With incoming
cotangents ``(dy, ds1, ds2)`` the chain rule collapses to a single
per-element total

    dy_total = dy + ds1[c] + 2 * y[r, c] * ds2[c]

followed by the standard matmul gradients ``dx = dy_total @ w^T`` and
``dw = x^T @ dy_total`` — the same contractions XLA runs for the unfused
conv, so the backward pays no extra passes beyond one fused elementwise
read of ``y``. Exactness vs the unfused composition is pinned in
tests/test_conv_bn.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Keep the whole [K, N] weight + one [block_m, K] input tile + the f32
# accumulator resident in VMEM; fall back to the unfused path when the
# estimate exceeds this budget (v4/v5 VMEM is 16 MB; leave headroom for
# Mosaic's own buffers).
_VMEM_BUDGET_BYTES = 13 * 1024 * 1024

_BLOCK_M_CANDIDATES = (512, 448, 256, 128, 64, 32, 16, 8)


def _pick_block_m(m: int) -> Optional[int]:
    for bm in _BLOCK_M_CANDIDATES:
        if m % bm == 0:
            return bm
    return None


def fits_fused(m: int, k: int, n: int, itemsize: int = 2) -> bool:
    """Whether the fused kernel's working set fits the VMEM budget."""
    bm = _pick_block_m(m) or 256
    weight = k * n * itemsize
    x_tile = bm * k * itemsize
    y_tile = bm * n * itemsize
    acc = bm * n * 4
    return weight + x_tile + y_tile + acc <= _VMEM_BUDGET_BYTES


def _fused_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    """One M-tile: matmul on the MXU, stats in the epilogue.

    s1/s2 use a constant index map, so their [1, N] block stays resident
    in VMEM across the whole (sequential) grid — the classic Pallas
    reduction-accumulator pattern.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    # f32 MXU accumulation for <=32-bit inputs; f64 only exists for the
    # float64 exactness probes in CI (TPUs have no f64 path).
    acc_t = (jnp.float64 if x_ref.dtype == jnp.float64 else jnp.float32)
    acc = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=acc_t)
    y_ref[...] = acc.astype(y_ref.dtype)
    # Statistics over the ROUNDED output (what the unfused path sees when
    # it upcasts the stored bf16 activation), so fused and unfused BN
    # consume identical moments.
    yr = y_ref[...].astype(s1_ref.dtype)
    ps1 = jnp.sum(yr, axis=0, keepdims=True)
    ps2 = jnp.sum(yr * yr, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = ps1
        s2_ref[...] = ps2

    @pl.when(i > 0)
    def _accum():
        s1_ref[...] += ps1
        s2_ref[...] += ps2


def _fused_forward(x, w, interpret: bool):
    """x [M, K], w [K, N] -> (y [M, N] x.dtype, s1 [N] f32, s2 [N] f32)."""
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[1]
    # Stats accumulate in f32 (f64 only under the CI exactness probes).
    stats_t = jnp.promote_types(jnp.float32, x.dtype)
    bm = _pick_block_m(m)
    pad = 0
    if bm is None:
        # Irregular row counts: zero rows contribute nothing to s1/s2 and
        # their y rows are sliced off below.
        bm = 256
        pad = (-m) % bm
        x = jnp.pad(x, ((0, pad), (0, 0)))
    # Under shard_map with check_vma=True (the default, kept on) Pallas
    # outputs must declare which mesh axes they vary over, and both dot
    # operands must agree — a replicated weight meeting a batch-sharded
    # activation needs an explicit pvary.
    try:
        x_vma = jax.typeof(x).vma
        w_vma = jax.typeof(w).vma
    except (AttributeError, TypeError):
        x_vma = w_vma = frozenset()
    if x_vma - w_vma:
        w = jax.lax.pcast(w, tuple(x_vma - w_vma), to="varying")
    if w_vma - x_vma:
        x = jax.lax.pcast(x, tuple(w_vma - x_vma), to="varying")
    vma = x_vma | w_vma

    def out_struct(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)

    y, s1, s2 = pl.pallas_call(
        _fused_kernel,
        grid=((m + pad) // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            out_struct((m + pad, n), x.dtype),
            out_struct((1, n), stats_t),
            out_struct((1, n), stats_t),
        ],
        interpret=interpret,
    )(x, w)
    if pad:
        y = y[:m]
    return y, s1[0], s2[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_bn_stats(x, w, interpret: bool = False):
    """Fused ``y = x @ w`` plus channel statistics ``(sum y, sum y^2)``.

    The statistics are computed over the rounded (storage-dtype) ``y`` in
    one pass while each tile is VMEM-resident. ``interpret=True`` runs
    the same kernel through the Pallas interpreter (CPU CI).
    """
    return _fused_forward(x, w, interpret)


def _matmul_bn_stats_fwd(x, w, interpret):
    y, s1, s2 = _fused_forward(x, w, interpret)
    return (y, s1, s2), (x, w, y)


def _matmul_bn_stats_bwd(interpret, res, cts):
    x, w, y = res
    dy, ds1, ds2 = cts
    # Collapse the three cotangent paths into one elementwise total (see
    # module docstring); XLA fuses the broadcasts + add with the matmul
    # operand preparation.
    acc_t = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
    dy_total = (dy.astype(acc_t)
                + ds1[None, :].astype(acc_t)
                + 2.0 * y.astype(acc_t) * ds2[None, :].astype(acc_t))
    dy_total = dy_total.astype(x.dtype)
    dx = jnp.dot(dy_total, w.T, preferred_element_type=acc_t)
    dw = jnp.dot(x.T, dy_total, preferred_element_type=acc_t)
    return dx.astype(x.dtype), dw.astype(w.dtype)


matmul_bn_stats.defvjp(_matmul_bn_stats_fwd, _matmul_bn_stats_bwd)


def conv1x1_bn_stats(x, w, strides: Tuple[int, int] = (1, 1),
                     interpret: Optional[bool] = None):
    """1x1 NHWC convolution with fused BN statistics.

    x [B, H, W, C_in], w [1, 1, C_in, C_out] (or [C_in, C_out]) ->
    (y [B, H', W', C_out], s1 [C_out], s2 [C_out]).

    A strided 1x1 conv only ever reads the stride-subsampled input, so it
    is the same matmul over ``x[:, ::sh, ::sw]`` — the slice is a strided
    HBM read of 1/(sh*sw) of the data, not an extra pass.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if w.ndim == 4:
        assert w.shape[:2] == (1, 1), w.shape
        w = w[0, 0]
    sh, sw = strides
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    b, h, wd, c = x.shape
    y, s1, s2 = matmul_bn_stats(x.reshape(b * h * wd, c), w, interpret)
    return y.reshape(b, h, wd, -1), s1, s2
