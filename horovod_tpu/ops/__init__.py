"""Compute ops: Pallas TPU kernels and XLA-fused building blocks."""

from horovod_tpu.ops.attention import (dot_product_attention,
                                       flash_attention, flash_grid_info)
from horovod_tpu.ops.conv_bn import (conv1x1_bn_stats,
                                     conv1x1_prologue_bn_stats)
from horovod_tpu.ops.xent import (fused_cross_entropy,
                                  tp_vocab_cross_entropy)

__all__ = [
    "dot_product_attention",
    "flash_attention",
    "flash_grid_info",
    "conv1x1_bn_stats",
    "conv1x1_prologue_bn_stats",
    "fused_cross_entropy",
    "tp_vocab_cross_entropy",
]
