"""Compute ops: Pallas TPU kernels and XLA-fused building blocks."""
