"""Fused paged-attention decode kernel: stream K/V pages, skip the gather.

vLLM's PagedAttention, rebuilt TPU-native on the machinery PR 3 shipped
in :mod:`horovod_tpu.ops.attention`: the serving engine's decode lane
(docs/serving.md) holds each request's KV cache as fixed-size pages
(``[num_pages, page_size, H, D]`` per layer per K/V) indexed by a
per-request page table, and the reference path reconstructs a dense
``[S, Lmax, H, D]`` logical cache per layer per step with a gather — so
a request at position ``t`` pays HBM traffic proportional to the
configured ``Lmax``, not to ``t``.

:func:`paged_attention_decode` kills that gather: a Pallas kernel whose
grid walks ``(slot, head, page-step)`` with the page tables and
per-slot lengths SCALAR-PREFETCHED (the ``PrefetchScalarGridSpec``
step-table technique of the packed causal flash grid), so each step's
K/V ``BlockSpec`` index maps straight to the slot's next PHYSICAL page
— Mosaic streams ``[page_size, D]`` K/V tiles through double-buffered
VMEM DMA while an online-softmax state (m/l/acc scratch) accumulates
across the page walk. The dense intermediate never exists, and the
pages a slot streams are exactly its ``ceil((t+1)/page_size)`` LIVE
pages:

* the page axis is the grid's innermost ("arbitrary") dimension, and
  steps past a slot's last live page clamp their index map to that
  last live page — an unchanged block index, so Mosaic's pipeline
  skips the re-fetch (no DMA) and ``pl.when`` skips the compute;
* idle lanes (length 0) park their index map on the reserved null
  page 0 and never compute — the null page's CONTENTS never enter an
  attention sum (tests fill it with NaN to prove it), and live slots
  never map it at all (their table entries below ``ceil((t+1)/ps)``
  are engine-mapped real pages);
* rows past ``t`` inside the last live page are masked to
  :data:`~horovod_tpu.ops.attention.NEG_INF` before the running max,
  exactly the reference cache mask.

Off-TPU the kernel runs in interpreter mode (the flash discipline), so
the whole path — ragged lengths, page-boundary edges, the null page —
is CI-pinned on CPU; :func:`paged_grid_info` is the static accounting
twin (the ``flash_grid_info`` pattern) that serve_bench stamps into
records and tests assert against.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from horovod_tpu.ops.attention import NEG_INF


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref,
                         o_ref, m_scr, l_scr, acc_scr, *,
                         page_size: int, scale: float):
    """One (slot, head, page-step) grid step.

    ``q_ref`` is the slot's single query row for this head
    ``[1, D]``; ``k_ref``/``v_ref`` are one physical page's slice for
    the head ``[page_size, 1, D]`` (the index maps resolved the page
    table BEFORE the body runs — scalar prefetch); the online-softmax
    state persists in VMEM scratch across the page walk (grid axis 2 is
    sequential). Shapes stay 2-D everywhere (the [1, D] query row is
    the MQA/GQA group-of-one layout the reference TPU paged-attention
    kernel uses; the statistics are [1, 1] columns — the Mosaic
    discipline of ops/attention.py)."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(2)
    live = lens_ref[s]                          # keys 0..t  (t+1 of them)
    live_pages = (live + page_size - 1) // page_size   # 0 for idle lanes

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(j < live_pages)
    def _compute():
        # Input-dtype matmuls with f32 accumulation (the flash-kernel
        # discipline); all softmax statistics stay f32.
        q = q_ref[...]                          # [1, D]
        k_blk = k_ref[...][:, 0, :]             # [ps, D]
        v_blk = v_ref[...][:, 0, :]
        sc = jnp.dot(q, k_blk.T,
                     preferred_element_type=jnp.float32) * scale  # [1, ps]
        # The cache mask: key positions past t (unwritten rows of the
        # last live page) contribute exactly zero — same NEG_INF
        # spelling as the reference kernel, applied BEFORE the running
        # max so garbage rows can never leak into the statistics.
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        sc = jnp.where(k_pos < live, sc, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sc - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)

    # Idle lanes (live_pages == 0) finalize at j == 0 with the zeroed
    # scratch: a deterministic all-zero output row (discarded upstream).
    @pl.when(j == jnp.maximum(live_pages - 1, 0))
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention_decode(q, k_pages, v_pages, tables, lengths,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Decode attention for S single-token queries straight from pages.

    Shapes::

        q        [S, H, D]        one query token per decode slot
        k_pages  [P, ps, H, D]    the physical page pool (page 0 = the
        v_pages  [P, ps, H, D]    reserved null sink, never streamed)
        tables   [S, pps] int32   per-slot logical->physical page table
        lengths  [S]      int32   live keys per slot (t+1; the row at t
                                  must already be scattered into its
                                  page — the kernel is READ-ONLY over
                                  pages); 0 marks an idle lane, whose
                                  output row is zeros

    Returns ``[S, H, D]``. Equals masked softmax attention over each
    slot's first ``lengths[s]`` gathered cache rows (the engine's
    ``_gather_cache`` + ``dot_product_attention(q_offset=t)`` reference
    path — pinned in tests/test_paged_attention.py); per-slot K/V bytes
    are ``ceil((t+1)/ps)`` pages instead of the gather's ``Lmax/ps``
    (:func:`paged_grid_info` is the static accounting).

    The engine contract (docs/serving.md): every table entry below
    ``ceil((t+1)/ps)`` is a MAPPED page (never 0) — the scheduler's
    ``ensure_pages``/reserve-admission invariant.

    ``interpret`` defaults to True off-TPU so the same kernel is
    CI-testable on the CPU mesh (the flash-kernel discipline).
    """
    from jax.experimental import pallas as pl

    from horovod_tpu.common.jax_compat import pallas_tpu
    pltpu = pallas_tpu()

    S, H, D = q.shape
    P, ps, Hk, Dk = k_pages.shape
    if (Hk, Dk) != (H, D) or v_pages.shape != k_pages.shape:
        raise ValueError(
            f"page/query shape mismatch: q {q.shape}, k_pages "
            f"{k_pages.shape}, v_pages {v_pages.shape}")
    if tables.shape[0] != S or lengths.shape != (S,):
        raise ValueError(
            f"tables {tables.shape} / lengths {lengths.shape} do not "
            f"match {S} slots")
    pps = tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def _page(s, j, tables, lengths):
        # The slot's next LIVE page; steps past the last live page
        # clamp to it (unchanged block index -> Mosaic skips the DMA),
        # and idle lanes (live_pages == 0) park on the null page 0
        # (their all-zero table) with compute fully skipped.
        live_pages = (lengths[s] + ps - 1) // ps
        return tables[s, jnp.minimum(j, jnp.maximum(live_pages - 1, 0))]

    kernel = functools.partial(_paged_decode_kernel, page_size=ps,
                               scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        # Page steps ride the INNERMOST axis (sequential, "arbitrary")
        # so the scratch-carried softmax state is legal while Mosaic
        # double-buffers the per-page K/V tile DMAs; slots and heads
        # are independent ("parallel").
        grid=(S, H, pps),
        in_specs=[
            pl.BlockSpec((None, 1, D), lambda s, h, j, t, ln: (s, h, 0)),
            pl.BlockSpec((None, ps, 1, D),
                         lambda s, h, j, t, ln: (_page(s, j, t, ln),
                                                 0, h, 0)),
            pl.BlockSpec((None, ps, 1, D),
                         lambda s, h, j, t, ln: (_page(s, j, t, ln),
                                                 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, 1, D),
                               lambda s, h, j, t, ln: (s, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),    # running max m
            pltpu.VMEM((1, 1), jnp.float32),    # running sum l
            pltpu.VMEM((1, D), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(lengths, jnp.int32),
      q, k_pages, v_pages)


# --------------------------------------------------------------------------
# Static accounting (the flash_grid_info pattern)


def paged_grid_info(lengths: Sequence[int], *, page_size: int,
                    pages_per_seq: int, num_heads: int, head_dim: int,
                    dtype_bytes: int = 4, num_layers: int = 1,
                    tables=None, tp: int = 1):
    """Static page/byte accounting for one decode step, without tracing.

    Mirrors exactly the index-map policy :func:`paged_attention_decode`
    runs — ``tools/serve_bench.py`` stamps this into serving records
    and tests assert against it, the way ``flash_grid_info`` backs the
    flash lanes.

    ``lengths`` are the per-slot live-key counts (``t+1``; 0 = idle
    lane). Returns a dict:

    * ``pages_live`` — per-slot pages streamed, ``ceil((t+1)/ps)``
      (0 for idle lanes: their block index parks on the null page with
      no compute);
    * ``pages_full`` — the gather path's per-slot page count,
      ``pages_per_seq = Lmax/ps`` for EVERY slot, idle included (the
      dense ``[S, Lmax, H, D]`` reconstruction has no length
      awareness);
    * ``kv_bytes`` / ``kv_bytes_gather`` — K+V bytes per decode step
      per the two policies (× ``num_layers``);
    * ``kv_fetch_frac`` — the streamed/gathered byte ratio, the
      traffic-win headline;
    * ``pages_visited`` (only when ``tables`` is given) — the per-slot
      PHYSICAL page ids the kernel's index map streams; never contains
      the null page 0 for a live slot;
    * ``tp`` / ``kv_bytes_per_chip`` / ``kv_bytes_gather_per_chip`` —
      the tensor-parallel degree and each policy's PER-CHIP bytes
      under it: heads shard exactly (``num_heads % tp == 0`` is
      enforced), so per-chip traffic is byte-for-byte 1/tp of the
      totals above — the honest form of the TP bandwidth claim
      (``tp=1`` degenerates to the totals).
    """
    lens = [int(x) for x in lengths]
    if any(x < 0 for x in lens):
        raise ValueError(f"negative length in {lens}")
    if tp < 1 or num_heads % tp != 0:
        raise ValueError(
            f"tp={tp} must be >= 1 and divide num_heads={num_heads} "
            "(the head-sharded page arrays split exactly)")
    pages_live = [-(-x // page_size) for x in lens]
    if any(p > pages_per_seq for p in pages_live):
        raise ValueError(
            f"length exceeds the page table: lengths {lens}, "
            f"pages_per_seq {pages_per_seq}, page_size {page_size}")
    S = len(lens)
    tile = 2 * page_size * num_heads * head_dim * dtype_bytes * num_layers
    info = {
        "page_size": page_size,
        "pages_per_seq": pages_per_seq,
        "slots": S,
        "pages_live": pages_live,
        "pages_live_total": sum(pages_live),
        "pages_full_total": S * pages_per_seq,
        "kv_bytes": sum(pages_live) * tile,
        "kv_bytes_gather": S * pages_per_seq * tile,
        "kv_fetch_frac": (round(sum(pages_live) / (S * pages_per_seq), 4)
                          if S else None),
        "tp": tp,
        "kv_bytes_per_chip": sum(pages_live) * tile // tp,
        "kv_bytes_gather_per_chip": S * pages_per_seq * tile // tp,
    }
    if tables is not None:
        import numpy as np

        tab = np.asarray(tables)
        if tab.shape != (S, pages_per_seq):
            raise ValueError(
                f"tables {tab.shape} does not match ({S}, "
                f"{pages_per_seq})")
        info["pages_visited"] = [
            [int(p) for p in tab[s, :pages_live[s]]] for s in range(S)]
    return info
