"""MNIST convnet — the smoke-test model.

Counterpart of the reference's ``examples/pytorch_mnist.py`` Net
(reference examples/pytorch_mnist.py:54-69): two convs + dropout + two
dense layers. Used by the single-process CPU smoke config in BASELINE.md.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MNISTNet(nn.Module):
    """Conv(10,5x5) -> pool -> Conv(20,5x5) -> pool -> 50 -> 10, NHWC."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(10, dtype=jnp.float32)(x)
        return x
