"""VGG family (11/13/16/19) — the reference's hardest scaling workload.

VGG-16 is the model the reference's published benchmarks scale WORST on
(68% efficiency at 512 GPUs vs 90% for ResNet — reference README.md:58,
docs/benchmarks.md:6) because its ~138M parameters make the gradient
allreduce enormous relative to compute. That makes it the stress test for
this framework's fused-bucket gradient psum. TPU-native choices mirror
resnet.py: NHWC, bfloat16 compute with fp32 params, static shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Convolution plans: ints are conv filter counts, "M" is 2x2 max-pool
# (the classic configurations A/B/D/E).
_PLANS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    """VGG with batch-norm (the variant every modern benchmark uses)."""

    depth: int = 16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    hidden: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        for step in _PLANS[self.depth]:
            if step == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = conv(features=step)(x)
                x = norm()(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for _ in range(2):
            x = nn.Dense(self.hidden, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


VGG11 = partial(VGG, depth=11)
VGG13 = partial(VGG, depth=13)
VGG16 = partial(VGG, depth=16)
VGG19 = partial(VGG, depth=19)
