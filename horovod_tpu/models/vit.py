"""Vision Transformer — beyond-reference model family.

The reference's benchmark set is all-convolutional (Inception/ResNet/VGG,
reference docs/benchmarks.md:5-6); ViT is the modern image classifier a
user switching frameworks expects to find, and on TPU it is the
best-case model: the whole forward is large batched matmuls on the MXU.
Reuses :class:`~horovod_tpu.models.transformer.TransformerBlock` with
non-causal dense attention (the block's pluggable ``attn_fn``), so the
parallelism stories (TP over heads, SP over patches via ring/Ulysses)
apply unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.models.transformer import TransformerBlock
from horovod_tpu.ops.attention import dot_product_attention


class VisionTransformer(nn.Module):
    """ViT encoder: patchify -> [CLS] + learned pos -> pre-norm blocks ->
    fp32 head. bf16 compute / fp32 norms+head, static shapes."""

    num_classes: int = 1000
    patch_size: int = 16
    embed_dim: int = 384
    depth: int = 12
    num_heads: int = 6
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        B = x.shape[0]
        if x.shape[1] % self.patch_size or x.shape[2] % self.patch_size:
            raise ValueError(
                f"image size {x.shape[1]}x{x.shape[2]} not divisible by "
                f"patch size {self.patch_size}"
            )
        x = jnp.asarray(x, self.dtype)
        # Patch embedding: one strided conv = per-patch linear projection
        # (VALID: partial zero-padded patches are not canonical ViT).
        x = nn.Conv(self.embed_dim, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    padding="VALID", dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(B, -1, self.embed_dim)  # [B, L, E]
        cls = self.param("cls", nn.initializers.zeros,
                         (1, 1, self.embed_dim), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, self.embed_dim)).astype(self.dtype),
             x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.embed_dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)

        bidirectional = partial(dot_product_attention, causal=False)
        for _ in range(self.depth):
            x = TransformerBlock(self.num_heads, dtype=self.dtype,
                                 attn_fn=bidirectional,
                                 dropout=self.dropout)(x, train=train)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0])


ViT_S16 = partial(VisionTransformer, patch_size=16, embed_dim=384,
                  depth=12, num_heads=6)
ViT_B16 = partial(VisionTransformer, patch_size=16, embed_dim=768,
                  depth=12, num_heads=12)
