"""Data-parallel training-step builder.

This is the TPU-native shape of "one training step, PyTorch" from the
reference (SURVEY §3.2; reference torch/__init__.py:95-151): forward, local
backward, cross-rank fused gradient allreduce, optimizer update. Under XLA
the whole sequence is one compiled program per chip; the reference's
background-thread negotiation and per-gradient hooks collapse into the
trace-time bucket fusion in :mod:`horovod_tpu.jax.fusion`.

Usage::

    state, optimizer = create_train_state(rng, model, optax.sgd(0.1), sample)
    step = make_train_step(model, optimizer)          # pure fn, jit/shard_map-able
    state, metrics = hvd.spmd_run(step, state, batch,
                                  in_specs=(P(), P("hvd")),
                                  out_specs=(P(), P()))

``create_train_state`` returns the (DistributedOptimizer-wrapped) optimizer
alongside the state; pass that same wrapped optimizer to
``make_train_step`` so ``opt_state`` and the update chain match.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.core import FrozenDict, freeze

from horovod_tpu.common.state import current_spmd_axis
from horovod_tpu.jax import mpi_ops
from horovod_tpu.jax.compression import Compression
from horovod_tpu.jax.optimizer import DistributedOptimizer


def cross_entropy_loss(logits, labels) -> jnp.ndarray:
    """Mean softmax cross-entropy against integer labels, in fp32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


class TrainState(Dict[str, Any]):
    """A plain pytree-of-arrays training state: params, batch_stats,
    opt_state, step. Dict subclass so it flows through jax transforms."""


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: (tuple(s[k] for k in sorted(s)), tuple(sorted(s))),
    lambda keys, vals: TrainState(zip(keys, vals)),
)


def create_train_state(
    rng,
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    distributed: bool = True,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    zero: bool = False,
    overlap: Optional[str] = None,
    hierarchical: Optional[str] = None,
) -> Tuple[TrainState, optax.GradientTransformation]:
    """Initialize params/batch_stats and the (wrapped) optimizer state.

    ``distributed=True`` wraps ``optimizer`` in :func:`DistributedOptimizer`
    — the one-line change the reference advertised
    (reference README.md:96-141).

    ``overlap`` (auto|on|off; default HOROVOD_OVERLAP) selects the
    backward-overlapped bucket schedule for the fused gradient exchange
    (:mod:`horovod_tpu.jax.fusion`): dispatch shape only, numerics are
    bit-identical across modes. Ignored with ``zero=True`` (the ZeRO
    path is already reduce-scatter shaped).

    ``hierarchical`` (auto|on|off; default HOROVOD_HIERARCHICAL) runs
    each gradient bucket as the two-level ICI/DCN ladder; with
    ``compression=Compression.int8``/``.fp8`` the DCN leg is quantized
    and the optimizer state carries rank-local error-feedback
    residuals — feed the state through :func:`state_partition_specs`
    (it maps them to ``P("hvd")``).

    ``zero=True`` uses ZeRO-1 optimizer-state sharding instead
    (:mod:`horovod_tpu.jax.zero`): same wire bytes, optimizer state and
    update FLOPs divided by the axis size. Feed the resulting state through
    the step with :func:`state_partition_specs` so the opt-state leaves are
    physically sharded.
    """
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    # Deep-freeze so the state's pytree TYPES are stable against what
    # the step emits (flax's mutable= collection comes back as a plain
    # dict on some versions) — lax.scan window loops require the carry
    # structure to match exactly, not just leaf-wise.
    batch_stats = freeze(variables.get("batch_stats", FrozenDict()))
    if zero:
        from horovod_tpu.jax.zero import sharded_distributed_optimizer

        optimizer = sharded_distributed_optimizer(
            optimizer, compression=compression
        )
        if backward_passes_per_step > 1:
            optimizer = optax.MultiSteps(
                optimizer, every_k_schedule=backward_passes_per_step
            ).gradient_transformation()
    elif distributed:
        optimizer = DistributedOptimizer(
            optimizer,
            compression=compression,
            backward_passes_per_step=backward_passes_per_step,
            overlap=overlap,
            hierarchical=hierarchical,
        )
    opt_state = optimizer.init(params)
    state = TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
    )
    return state, optimizer


def apply_gradients(
    optimizer: optax.GradientTransformation,
    state: TrainState,
    grads,
    batch_stats=None,
) -> TrainState:
    """The shared update tail of every training step: optimizer update
    (the DistributedOptimizer/ZeRO wrapper performs the fused cross-rank
    gradient exchange here), parameter apply, state repack with the step
    counter advanced."""
    updates, new_opt_state = optimizer.update(
        grads, state["opt_state"], state["params"]
    )
    return TrainState(
        params=optax.apply_updates(state["params"], updates),
        batch_stats=state["batch_stats"] if batch_stats is None else batch_stats,
        opt_state=new_opt_state,
        step=state["step"] + 1,
    )


def make_train_step(model, optimizer: optax.GradientTransformation, average_loss: bool = True):
    """Build the per-rank SPMD training step.

    The returned function takes ``(state, batch)`` where ``batch`` is the
    *per-rank* shard ``{"image": ..., "label": ...}``, and returns
    ``(new_state, metrics)``. Collectives inside (gradient psum from
    DistributedOptimizer, loss pmean) activate when run under
    ``hvd.spmd_run``; outside SPMD (single process eager) they are
    identities, matching the reference's size()==1 degradation.
    """

    def loss_fn(params, batch_stats, batch, rng):
        outputs, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
        loss = cross_entropy_loss(outputs, batch["label"])
        # freeze: scan-carry type stability (see create_train_state).
        return loss, (freeze(mutated.get("batch_stats", FrozenDict())),
                      outputs)

    def train_step(state, batch):
        # Deterministic per-step dropout key, decorrelated across ranks
        # under SPMD (each rank folds in its axis index).
        rng = jax.random.fold_in(jax.random.PRNGKey(0), state["step"])
        axis = current_spmd_axis()
        if axis is not None:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        (loss, (new_stats, logits)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], state["batch_stats"], batch, rng
        )
        accuracy = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        if average_loss:
            loss = mpi_ops.allreduce(loss, average=True, name="train.loss")
            accuracy = mpi_ops.allreduce(accuracy, average=True, name="train.accuracy")
        new_state = apply_gradients(optimizer, state, grads,
                                    batch_stats=new_stats)
        return new_state, {"loss": loss, "accuracy": accuracy}

    return train_step


def make_windowed_train_step(model, optimizer: optax.GradientTransformation,
                             steps_per_dispatch: int,
                             average_loss: bool = True):
    """Window-loop form of :func:`make_train_step`: K steps compiled
    into ONE ``lax.scan`` program (:mod:`horovod_tpu.jax.window`), so
    the host dispatches once per window instead of once per step — the
    fix for the measured 27-32% host-dispatch gap on short-step models
    (PERF.md round 5).

    The returned function takes ``(state, stacked_batches)`` where every
    batch leaf carries a leading window axis of length
    ``steps_per_dispatch`` (stage them with
    :func:`horovod_tpu.data.prefetch_windows`), and returns
    ``(new_state, metric_means)``. ``steps_per_dispatch=1`` degrades to
    exactly :func:`make_train_step`'s per-step form. For the full
    stage-and-dispatch loop use ``hvd.run_steps`` directly::

        step = make_train_step(model, optimizer)
        state, metrics = hvd.run_steps(step, state, batch_iter,
                                       steps_per_dispatch=30)
    """
    from horovod_tpu.jax.window import windowed

    return windowed(make_train_step(model, optimizer, average_loss),
                    steps_per_dispatch)


def state_partition_specs(state: TrainState):
    """Partition-spec pytree for a :class:`TrainState`: everything
    replicated except the rank-sharded optimizer-state vectors —
    ZeRO-sharded flats and hierarchical error-feedback residuals — which
    shard over the data axis resolved through the bound
    :class:`~horovod_tpu.parallel.logical.LogicalMesh` rules table
    (legacy ``P("hvd")`` when none is bound). Pass as both ``in_specs``
    and the state half of ``out_specs`` when training with
    ``create_train_state(..., zero=True)`` or with a low-bit DCN wire
    codec (``compression=Compression.int8`` / ``.fp8`` +
    hierarchical)."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.jax import zero as _zero
    from horovod_tpu.jax.optimizer import (
        _AllreduceState,
        ef_state_partition_specs,
    )
    from horovod_tpu.parallel.logical import module_axis

    data_axis = module_axis("data")

    def spec_for(node):
        if isinstance(node, _zero.ZeroState):
            return _zero.state_partition_specs(node, axis_name=data_axis)
        if isinstance(node, _AllreduceState):
            return ef_state_partition_specs(node, axis_name=data_axis)
        return P()

    opt_spec = _jax.tree_util.tree_map(
        spec_for, state["opt_state"],
        is_leaf=lambda n: isinstance(n, (_zero.ZeroState,
                                         _AllreduceState)))
    return TrainState(
        params=P(),
        batch_stats=P(),
        opt_state=opt_spec,
        step=P(),
    )


def make_eval_step(model):
    """Per-rank evaluation step returning summed (correct, count) so the
    caller can allreduce totals (the reference's metric-average pattern,
    examples/pytorch_mnist.py:120-133)."""

    def eval_step(state, batch):
        logits = model.apply(
            {"params": state["params"], "batch_stats": state["batch_stats"]},
            batch["image"],
            train=False,
        )
        loss = cross_entropy_loss(logits, batch["label"])
        correct = jnp.sum((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        return {"loss": loss, "correct": correct, "count": jnp.asarray(batch["label"].shape[0], jnp.float32)}

    return eval_step
