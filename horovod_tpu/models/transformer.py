"""Decoder-only Transformer LM — the long-context flagship.

Beyond-reference model family (the reference's longest-context artifact is
word2vec, SURVEY §2.9): a GPT-style causal LM whose attention is pluggable
so the same network trains single-chip (flash attention on the MXU),
sequence-parallel via ring attention, or via Ulysses all-to-all — the
framework's long-context story end to end.

TPU-native choices: bf16 compute / fp32 layernorm+softmax+logits, static
shapes, pre-norm blocks, learned positional embeddings, no Python control
flow in the forward pass.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.ops.attention import dot_product_attention


class TransformerBlock(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    mlp_ratio: int = 4
    # attn_fn(q, k, v) -> out, shapes [B, L, H, D]. The fn owns causality
    # and cross-shard positioning (e.g. a ring-attention closure passes
    # causal=True itself; ring/Ulysses derive offsets from the mesh axis).
    # None = dense causal attention using q_offset.
    attn_fn: Optional[Callable] = None
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True, q_offset: int = 0):
        E = x.shape[-1]
        H = self.num_heads
        D = E // H
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        qkv = nn.Dense(3 * E, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (*q.shape[:-1], H, D)
        if self.attn_fn is None:
            attn = dot_product_attention(
                q.reshape(shape), k.reshape(shape), v.reshape(shape),
                causal=True, q_offset=q_offset)
        else:
            attn = self.attn_fn(q.reshape(shape), k.reshape(shape),
                                v.reshape(shape))
        attn = attn.reshape(q.shape)
        x = x + nn.Dense(E, dtype=self.dtype)(attn)

        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(self.mlp_ratio * E, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + nn.Dense(E, dtype=self.dtype)(h)


class _CarryBlock(nn.Module):
    """TransformerBlock with a (carry, _) -> (carry, None) signature so
    ``nn.scan`` can stack it along a layer axis."""

    num_heads: int
    dtype: Any
    attn_fn: Optional[Callable]
    dropout: float
    train: bool
    q_offset: int

    @nn.compact
    def __call__(self, x, _):
        x = TransformerBlock(self.num_heads, dtype=self.dtype,
                             attn_fn=self.attn_fn, dropout=self.dropout)(
                                 x, train=self.train, q_offset=self.q_offset)
        return x, None


class TransformerLM(nn.Module):
    """Causal LM: token ids [B, L] -> logits [B, L, vocab].

    ``scan_layers`` compiles the layer stack as ONE ``lax.scan`` step
    over weight-stacked parameters instead of ``num_layers`` unrolled
    copies — XLA traces/compiles a single block, so compile time is
    ~flat in depth (the unrolled path grows linearly; on a tunneled
    backend where big first-compiles time out, that is the difference
    between a recorded benchmark and none). Parameters change layout
    (each block param gains a leading [num_layers] axis), so the two
    layouts are not checkpoint-compatible; per-layer math is identical
    (equivalence pinned in tests/test_models.py). ``remat`` additionally
    rematerializes each block on the backward pass — activation memory
    O(1) in depth, the long-context training default.
    """

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    embed_dim: int = 512
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    dropout: float = 0.0
    scan_layers: bool = False
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = True, pos_offset: int = 0,
                 return_hidden: bool = False):
        """``pos_offset``: global position of tokens[:, 0] — sequence-
        parallel callers pass their shard's offset so positional
        embeddings and causal masks stay globally consistent.

        ``return_hidden`` skips the vocab projection and returns the
        final-LayerNorm hidden states [B, L, E] — for fused losses
        (ops/xent.py) that consume the projection weight directly and
        never materialize [B, L, vocab] logits. Init with the default
        so the Dense param exists either way."""
        x = nn.Embed(self.vocab_size, self.embed_dim,
                     dtype=self.dtype)(tokens)
        pos = pos_offset + jnp.arange(tokens.shape[1])
        x = x + nn.Embed(self.max_len, self.embed_dim,
                         dtype=self.dtype)(pos)[None]
        if self.scan_layers:
            block = _CarryBlock
            if self.remat:
                block = nn.remat(block, prevent_cse=False)
            scan = nn.scan(block,
                           variable_axes={"params": 0},
                           split_rngs={"params": True, "dropout": True},
                           length=self.num_layers)
            x, _ = scan(self.num_heads, self.dtype, self.attn_fn,
                        self.dropout, train, pos_offset,
                        name="layers")(x, None)
        else:
            blk = TransformerBlock
            if self.remat:
                # self=0, x=1: train and q_offset stay Python-static.
                blk = nn.remat(blk, prevent_cse=False,
                               static_argnums=(2, 3))
            for i in range(self.num_layers):
                # Explicit names keep the param tree identical whether
                # or not the block is remat-wrapped (nn.remat would
                # otherwise prefix the auto-name with "Checkpoint").
                x = blk(self.num_heads, dtype=self.dtype,
                        attn_fn=self.attn_fn, dropout=self.dropout,
                        name=f"TransformerBlock_{i}")(x, train, pos_offset)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        if return_hidden:
            return x
        # Explicitly named so fused losses can address the projection
        # weight (params["lm_head"]["kernel"]) without depending on
        # flax auto-numbering staying stable.
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        use_bias=False, name="lm_head")(x)
