"""ResNet model family (18/34/50/101/152) — the benchmark workload.

The reference's canonical scaling benchmark is torchvision ResNet-50 under
``examples/pytorch_synthetic_benchmark.py`` (reference
examples/pytorch_synthetic_benchmark.py:24-35,92-110) and its published
efficiency numbers are ResNet-class (reference docs/benchmarks.md:5-6).
This is the TPU-native counterpart, written for the MXU rather than
translated from torchvision:

* **NHWC layout** — the native TPU convolution layout (torchvision is NCHW).
* **bfloat16 compute, fp32 params/statistics** — conv/matmul FLOPs run on
  the MXU in bf16; parameters, batch-norm statistics, and the softmax are
  kept in fp32 for stability.
* **Cross-replica BatchNorm option** — under SPMD the per-chip batch is the
  global batch / N; passing ``axis_name="hvd"`` syncs moments over the ICI
  (the reference had no sync-BN; each worker normalized locally — that is
  the default here too).
* **Fused conv+BN-statistics option** (``fused_bn=True``) — every conv+BN
  pair goes through one :class:`ConvBN` module; the 1x1 convolutions (36
  of ResNet-50's 53) then compute their channel statistics in the matmul
  epilogue via the Pallas kernel in :mod:`horovod_tpu.ops.conv_bn`,
  eliminating the separate statistics read over each conv output that
  profiling showed to be the largest single step-time sink (PERF.md).
* Static shapes and no Python control flow in the forward pass: one XLA
  program, fully fusable.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.conv_bn import (
    conv1x1_bn_stats,
    conv1x1_prologue_bn_stats,
    fits_fused,
)

ModuleDef = Any


class ConvBN(nn.Module):
    """Bias-free convolution + BatchNorm as ONE module.

    Keeping the pair in one module lets the 1x1 case run the fused Pallas
    matmul+statistics kernel (``fuse=True``) while every other case takes
    the standard XLA conv + reduction path — with an IDENTICAL parameter
    tree, so fused-vs-unfused exactness is testable with shared weights
    (tests/test_conv_bn.py).

    Parameters/variables: ``kernel`` (fp32, cast to ``dtype`` for
    compute), BN ``scale``/``bias`` (fp32), running ``batch_stats``
    ``mean``/``var`` (fp32). Statistics always use the fast-variance form
    ``E[y^2] - E[y]^2`` so both paths consume the same moments.
    """

    features: int
    kernel_size: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    scale_init: Callable = nn.initializers.ones_init()
    fuse: bool = False
    # emit_raw=True returns (raw_conv_output, mul, add) instead of the
    # normalized output: the consumer folds the BatchNorm apply (+ReLU)
    # into its own kernel's PROLOGUE (phase-2 fusion; see
    # ops/conv_bn.py). Statistics and running averages still update.
    emit_raw: bool = False

    @nn.compact
    def __call__(self, x, prologue=None):
        """``prologue``: optional ``(mul, add)`` of the PRODUCING layer;
        this layer's input ``x`` is then that layer's RAW output and the
        normalize + ReLU happens in the fused kernel's prologue (1x1
        fused path) or as an explicit elementwise fallback."""
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, cin, self.features), jnp.float32)
        scale = self.param(
            "scale", self.scale_init, (self.features,), jnp.float32)
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,),
            jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.features,), jnp.float32))
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.features,), jnp.float32))

        x = jnp.asarray(x, self.dtype)
        k = jnp.asarray(kernel, self.dtype)

        def apply_prologue(inputs):
            # Same elementwise math the fused prologue runs in-kernel.
            p_mul, p_add = prologue
            return jnp.maximum(
                inputs * p_mul.astype(self.dtype)
                + p_add.astype(self.dtype), 0)

        def conv(inputs):
            return lax.conv_general_dilated(
                inputs, k, window_strides=self.strides,
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=self.dtype)

        can_fuse = (
            self.fuse
            and not self.use_running_average
            and (kh, kw) == (1, 1)
            and isinstance(self.padding, str)
            and fits_fused(
                (x.shape[0] * x.shape[1] * x.shape[2])
                // (self.strides[0] * self.strides[1]),
                cin, self.features,
                itemsize=jnp.dtype(self.dtype).itemsize)
        )
        if self.use_running_average:
            y = conv(apply_prologue(x) if prologue is not None else x)
            mean, var = ra_mean.value, ra_var.value
        else:
            if can_fuse:
                if prologue is not None:
                    y, s1, s2 = conv1x1_prologue_bn_stats(
                        x, prologue[0], prologue[1], k, self.strides)
                else:
                    y, s1, s2 = conv1x1_bn_stats(x, k, self.strides)
                n = jnp.asarray(
                    y.shape[0] * y.shape[1] * y.shape[2], jnp.float32)
                if self.axis_name is not None:
                    s1 = lax.psum(s1, self.axis_name)
                    s2 = lax.psum(s2, self.axis_name)
                    n = lax.psum(n, self.axis_name)
                mean = s1 / n
                var = s2 / n - mean * mean
            else:
                y = conv(apply_prologue(x) if prologue is not None else x)
                yf = y.astype(jnp.promote_types(jnp.float32, y.dtype))
                mean = jnp.mean(yf, axis=(0, 1, 2))
                msq = jnp.mean(yf * yf, axis=(0, 1, 2))
                if self.axis_name is not None:
                    mean = lax.pmean(mean, self.axis_name)
                    msq = lax.pmean(msq, self.axis_name)
                var = msq - mean * mean
            if not self.is_initializing() and self.is_mutable_collection(
                    "batch_stats"):
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        mul = scale * lax.rsqrt(var + self.epsilon)
        add = bias - mean * mul
        if self.emit_raw:
            return y, mul, add
        return y * mul.astype(self.dtype) + add.astype(self.dtype)


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv_bn: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv_bn(self.filters, (3, 3), self.strides)(x)
        y = self.act(y)
        y = self.conv_bn(
            self.filters, (3, 3),
            scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv_bn(
                self.filters, (1, 1), self.strides, name="proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck block (ResNet-50/101/152).

    ``prologue_fuse``: the 3x3's normalized+ReLU'd output is consumed
    ONLY by the last 1x1, so its BatchNorm apply moves into that 1x1
    kernel's prologue — the intermediate never reaches HBM (phase-2
    fusion, ops/conv_bn.py; requires the activation to be ReLU)."""

    filters: int
    conv_bn: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)
    prologue_fuse: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv_bn(self.filters, (1, 1))(x)
        y = self.act(y)
        # Zero-init the last norm scale so each block starts as identity:
        # standard large-batch ResNet recipe (Goyal et al.), which the
        # reference applied via its LR-warmup callbacks instead.
        if self.prologue_fuse:
            raw, mul2, add2 = self.conv_bn(
                self.filters, (3, 3), self.strides, emit_raw=True)(y)
            y = self.conv_bn(
                self.filters * 4, (1, 1),
                scale_init=nn.initializers.zeros_init())(
                    raw, prologue=(mul2, add2))
        else:
            y = self.conv_bn(self.filters, (3, 3), self.strides)(y)
            y = self.act(y)
            y = self.conv_bn(
                self.filters * 4, (1, 1),
                scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv_bn(
                self.filters * 4, (1, 1), self.strides,
                name="proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ImageNet-style ResNet over NHWC inputs.

    ``axis_name`` enables cross-replica BatchNorm moments under SPMD.
    ``fused_bn`` routes the 1x1 conv+BN pairs through the Pallas fused
    statistics kernel (training mode only; eval always uses the plain
    conv since running statistics need no reduction).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    axis_name: Optional[str] = None
    fused_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv_bn = partial(
            ConvBN,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
            fuse=self.fused_bn,
        )
        x = jnp.asarray(x, self.dtype)
        x = conv_bn(
            self.num_filters, (7, 7), (2, 2),
            padding=[(3, 3), (3, 3)], name="stem")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        # Phase-2 prologue fusion bakes a ReLU into the kernel, so it is
        # only wired for the canonical activation.
        block_kwargs = {}
        if (self.fused_bn and self.act is nn.relu
                and self.block_cls is BottleneckResNetBlock):
            block_kwargs["prologue_fuse"] = True
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv_bn=conv_bn,
                    act=self.act,
                    **block_kwargs,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return jnp.asarray(x, jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckResNetBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckResNetBlock)

_FAMILY = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}


def build(name: str, **kwargs) -> nn.Module:
    """Construct a ResNet by torchvision-style name (the reference benchmark
    selected models via ``getattr(torchvision.models, args.model)``,
    examples/pytorch_synthetic_benchmark.py:55)."""
    try:
        return _FAMILY[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(f"Unknown ResNet variant {name!r}; have {sorted(_FAMILY)}")
