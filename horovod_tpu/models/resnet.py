"""ResNet model family (18/34/50/101/152) — the benchmark workload.

The reference's canonical scaling benchmark is torchvision ResNet-50 under
``examples/pytorch_synthetic_benchmark.py`` (reference
examples/pytorch_synthetic_benchmark.py:24-35,92-110) and its published
efficiency numbers are ResNet-class (reference docs/benchmarks.md:5-6).
This is the TPU-native counterpart, written for the MXU rather than
translated from torchvision:

* **NHWC layout** — the native TPU convolution layout (torchvision is NCHW).
* **bfloat16 compute, fp32 params/statistics** — conv/matmul FLOPs run on
  the MXU in bf16; parameters, batch-norm statistics, and the softmax are
  kept in fp32 for stability.
* **Cross-replica BatchNorm option** — under SPMD the per-chip batch is the
  global batch / N; passing ``axis_name="hvd"`` syncs moments over the ICI
  (the reference had no sync-BN; each worker normalized locally — that is
  the default here too).
* Static shapes and no Python control flow in the forward pass: one XLA
  program, fully fusable.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last norm scale so each block starts as identity:
        # standard large-batch ResNet recipe (Goyal et al.), which the
        # reference applied via its LR-warmup callbacks instead.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ImageNet-style ResNet over NHWC inputs.

    ``axis_name`` enables cross-replica BatchNorm moments under SPMD.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
        )
        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return jnp.asarray(x, jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckResNetBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckResNetBlock)

_FAMILY = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}


def build(name: str, **kwargs) -> nn.Module:
    """Construct a ResNet by torchvision-style name (the reference benchmark
    selected models via ``getattr(torchvision.models, args.model)``,
    examples/pytorch_synthetic_benchmark.py:55)."""
    try:
        return _FAMILY[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(f"Unknown ResNet variant {name!r}; have {sorted(_FAMILY)}")
