"""Inception V3 — the reference's headline 90%-efficiency workload.

The reference's published scaling table leads with Inception V3 (90% at
512 GPUs — reference README.md:53-58, docs/benchmarks.md:5-6); its
benchmark harness ran it via tf_cnn_benchmarks. This is a TPU-native
flax implementation of the standard architecture (Szegedy et al. 2015,
the torchvision/slim layer plan): NHWC, bf16 compute / fp32 norms,
static shapes, no aux head by default (benchmarks run without it).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.models.resnet import ConvBN as _SharedConvBN


class ConvBN(nn.Module):
    """Conv + BN + ReLU through the shared :class:`resnet.ConvBN`, so the
    many 1x1 convolutions Inception is built from can run the fused
    Pallas matmul + statistics kernel (``fuse=True``; phase-1 only —
    Inception's 1x1 outputs feed non-1x1 consumers, so the prologue
    variant does not apply)."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        y = _SharedConvBN(self.features, self.kernel, self.strides,
                          padding=self.padding,
                          use_running_average=not train, momentum=0.9,
                          epsilon=1e-3, dtype=self.dtype,
                          fuse=self.fuse)(x)
        return nn.relu(y)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fuse)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train),
                           train)
        pool = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(self.pool_features, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    dtype: Any
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fuse)
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(96, (3, 3), strides=(2, 2), padding="VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        pool = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, pool], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fuse)
        c7 = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(c7, (1, 1))(x, train)
        b2 = c(c7, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b3 = c(c7, (1, 1))(x, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(c7, (1, 7))(b3, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(192, (1, 7))(b3, train)
        pool = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    dtype: Any
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fuse)
        b1 = c(320, (3, 3), strides=(2, 2), padding="VALID")(
            c(192, (1, 1))(x, train), train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(192, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b2 = c(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        pool = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, pool], axis=-1)


class InceptionE(nn.Module):
    dtype: Any
    fuse: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fuse)
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([c(384, (1, 3))(b2, train),
                              c(384, (3, 1))(b2, train)], axis=-1)
        b3 = c(448, (1, 1))(x, train)
        b3 = c(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([c(384, (1, 3))(b3, train),
                              c(384, (3, 1))(b3, train)], axis=-1)
        pool = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """Standard 299x299 Inception V3 (torchvision layer plan)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    fused_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype, fuse=self.fused_bn)
        x = x.astype(self.dtype)
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        f = self.fused_bn
        x = InceptionA(32, self.dtype, f)(x, train)
        x = InceptionA(64, self.dtype, f)(x, train)
        x = InceptionA(64, self.dtype, f)(x, train)
        x = InceptionB(self.dtype, f)(x, train)
        x = InceptionC(128, self.dtype, f)(x, train)
        x = InceptionC(160, self.dtype, f)(x, train)
        x = InceptionC(160, self.dtype, f)(x, train)
        x = InceptionC(192, self.dtype, f)(x, train)
        x = InceptionD(self.dtype, f)(x, train)
        x = InceptionE(self.dtype, f)(x, train)
        x = InceptionE(self.dtype, f)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
