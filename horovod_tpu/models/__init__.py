"""Model zoo used by the examples, benchmarks, and tests.

Families mirror the reference's published benchmark set (Inception V3,
ResNet, VGG — reference docs/benchmarks.md:5-6) plus the long-context
Transformer LM this rebuild adds as a first-class workload.
"""

from horovod_tpu.models.inception import InceptionV3
from horovod_tpu.models.mnist import MNISTNet
from horovod_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.resnet import _FAMILY as _RESNET_FAMILY
from horovod_tpu.models.train import (
    TrainState,
    apply_gradients,
    create_train_state,
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
    make_windowed_train_step,
    state_partition_specs,
)
from horovod_tpu.models import parallel_lm
from horovod_tpu.models.transformer import TransformerBlock, TransformerLM
from horovod_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19
from horovod_tpu.models.vit import ViT_B16, ViT_S16, VisionTransformer

_FAMILY = dict(_RESNET_FAMILY)
_FAMILY.update({
    "vgg11": VGG11,
    "vgg13": VGG13,
    "vgg16": VGG16,
    "vgg19": VGG19,
    "inception_v3": InceptionV3,
    "inception3": InceptionV3,
    "transformer_lm": TransformerLM,
    "vit_s16": ViT_S16,
    "vit_b16": ViT_B16,
})


def build(name: str, **kwargs):
    """Construct any zoo model by torchvision-style name (the reference
    benchmark selected models via ``getattr(torchvision.models, ...)``,
    examples/pytorch_synthetic_benchmark.py:55)."""
    try:
        return _FAMILY[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(
            f"Unknown model {name!r}; have {sorted(_FAMILY)}") from None


__all__ = [
    "MNISTNet",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "VGG",
    "VGG11",
    "VGG13",
    "VGG16",
    "VGG19",
    "InceptionV3",
    "TransformerBlock",
    "TransformerLM",
    "VisionTransformer",
    "ViT_S16",
    "ViT_B16",
    "build",
    "TrainState",
    "apply_gradients",
    "parallel_lm",
    "create_train_state",
    "cross_entropy_loss",
    "make_eval_step",
    "make_train_step",
    "make_windowed_train_step",
    "state_partition_specs",
]
