"""Model zoo used by the examples, benchmarks, and tests."""

from horovod_tpu.models.mnist import MNISTNet
from horovod_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
    build,
)
from horovod_tpu.models.train import (
    TrainState,
    create_train_state,
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "MNISTNet",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "build",
    "TrainState",
    "create_train_state",
    "cross_entropy_loss",
    "make_eval_step",
    "make_train_step",
]
