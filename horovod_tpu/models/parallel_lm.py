"""Composed-parallelism GPT-style LM: dp x sp x tp in one model.

The reference scaled batch only (SURVEY §2.9: no TP/SP anywhere); this
module is the TPU-native flagship composition the parallel/ primitives
exist for, packaged as a first-class model instead of a hand-assembled
example:

* **tp** — attention heads and MLP features shard Megatron-style
  (:mod:`horovod_tpu.parallel.tp`): column-parallel QKV/up-projection
  (no comm), row-parallel out/down-projection (one psum each);
* **sp** — the sequence axis shards across chips and attention runs the
  exact ring schedule (:mod:`horovod_tpu.parallel.ring_attention`),
  with positional embeddings and the causal mask taken at global
  positions;
* **dp** — data parallelism is the caller's batch sharding plus the
  uniform gradient pmean this module's loss helper pairs with.

Everything is pure functions over an explicit parameter pytree, the
idiom of :mod:`horovod_tpu.parallel`: build DENSE (unsharded) params
with :func:`init_lm_params`, hand them to ``shard_map`` with
:func:`lm_param_specs` as ``in_specs`` — the mesh slices the dense
arrays onto chips — and call :func:`lm_apply` inside. With
``sp=tp=None`` the same functions run the dense math on one device,
which is exactly what the exactness tests compare against.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.attention import dot_product_attention
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.tp import (
    sum_across,
    tp_mlp,
    tp_region_input,
    tp_region_output,
)


def init_lm_params(rng, vocab: int, max_len: int, layers: int, heads: int,
                   head_dim: int, ffn: int, dtype=jnp.float32) -> Dict:
    """Dense (unsharded) parameter pytree. Shapes keep the head and
    feature axes explicit so the tp specs can shard them:
    wqkv [E, 3, H, Dh], wo [H, Dh, E], wup [E, F], wdn [F, E]."""
    embed_dim = heads * head_dim
    keys = jax.random.split(rng, 2 * layers + 3)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape) / math.sqrt(fan_in)).astype(dtype)

    params: Dict[str, Any] = {
        "embed": dense_init(keys[0], (vocab, embed_dim), embed_dim),
        "pos": dense_init(keys[1], (max_len, embed_dim), embed_dim),
        "layers": [],
        "ln_f": {"g": jnp.ones((embed_dim,), dtype),
                 "b": jnp.zeros((embed_dim,), dtype)},
        "head": dense_init(keys[2], (embed_dim, vocab), embed_dim),
    }
    for i in range(layers):
        ka, kb, kc = jax.random.split(keys[3 + 2 * i], 3)
        kd = keys[4 + 2 * i]
        params["layers"].append({
            "ln1": {"g": jnp.ones((embed_dim,), dtype),
                    "b": jnp.zeros((embed_dim,), dtype)},
            "wqkv": dense_init(ka, (embed_dim, 3, heads, head_dim),
                               embed_dim),
            "wo": dense_init(kb, (heads, head_dim, embed_dim), embed_dim),
            "bo": jnp.zeros((embed_dim,), dtype),
            "ln2": {"g": jnp.ones((embed_dim,), dtype),
                    "b": jnp.zeros((embed_dim,), dtype)},
            "wup": dense_init(kc, (embed_dim, ffn), embed_dim),
            "bup": jnp.zeros((ffn,), dtype),
            "wdn": dense_init(kd, (ffn, embed_dim), ffn),
            "bdn": jnp.zeros((embed_dim,), dtype),
        })
    return params


def lm_param_specs(layers: int, tp_axis: Optional[str],
                   vocab_parallel: bool = False):
    """PartitionSpec pytree matching :func:`init_lm_params`' structure.

    Pass as the params entry of ``shard_map``'s ``in_specs`` (and
    ``out_specs`` for the updated state): the mesh then slices the DENSE
    arrays — heads/features over ``tp_axis``, everything else
    replicated. ``tp_axis=None`` replicates everything.
    ``vocab_parallel`` additionally shards the vocab projection
    [E, V] over ``tp_axis`` — pair with
    :func:`next_token_nll_fused`'s vocab-parallel loss (the plain
    :func:`lm_apply` logits path assumes a replicated head)."""
    from jax.sharding import PartitionSpec as P

    t = tp_axis
    layer_spec = {
        "ln1": {"g": P(), "b": P()},
        "wqkv": P(None, None, t, None),
        "wo": P(t, None, None),
        "bo": P(),
        "ln2": {"g": P(), "b": P()},
        "wup": P(None, t),
        "bup": P(t),
        "wdn": P(t, None),
        "bdn": P(),
    }
    return {
        "embed": P(),
        "pos": P(),
        "layers": [dict(layer_spec) for _ in range(layers)],
        "ln_f": {"g": P(), "b": P()},
        "head": P(None, t) if vocab_parallel else P(),
    }


def _layernorm(x, g, b):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-5)).astype(x.dtype) * g + b


def _project_qkv(layer, x, tp):
    """ln1 -> (Megatron f) -> fused QKV projection onto local heads."""
    a = _layernorm(x, layer["ln1"]["g"], layer["ln1"]["b"])
    if tp:
        # Megatron f: upstream grads must SUM the per-head-shard
        # cotangents (identity fwd, psum bwd).
        a = tp_region_input(a, tp)
    qkv = jnp.einsum("ble,ethd->blthd", a, layer["wqkv"])
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _attn_out_residual(layer, attn, x, tp):
    """Row-parallel output projection (Megatron g) + residual."""
    proj = jnp.einsum("blhd,hde->ble", attn, layer["wo"])
    if tp:
        proj = tp_region_output(proj, tp)
    return x + proj + layer["bo"]


def _ffn_residual(layer, x, tp):
    m = _layernorm(x, layer["ln2"]["g"], layer["ln2"]["b"])
    if tp:
        m = tp_region_input(m, tp)
        return x + tp_mlp(m, layer["wup"], layer["bup"], layer["wdn"],
                          layer["bdn"], axis=tp)
    h = jax.nn.gelu(m @ layer["wup"] + layer["bup"])
    return x + h @ layer["wdn"] + layer["bdn"]


def _final_hidden(params, x):
    return _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])


def _logits(params, x, tp=None, vocab_parallel: bool = False):
    """Final LayerNorm + vocab projection -> full-vocab logits.

    With ``vocab_parallel`` the head arrives column-sharded [E, V/tp]
    (:func:`lm_param_specs` ``vocab_parallel=True``) and the full row
    is assembled by ONE tiled all-gather
    (:func:`~horovod_tpu.parallel.tp.vocab_parallel_logits`) — the
    serving path's spelling; training-side fused losses consume the
    shard directly and never materialize this tensor."""
    h = _final_hidden(params, x)
    if vocab_parallel:
        if not tp:
            raise ValueError("vocab_parallel logits need a tp axis")
        from horovod_tpu.parallel.tp import vocab_parallel_logits

        return vocab_parallel_logits(h, params["head"], axis=tp)
    return h @ params["head"]


def lm_apply(params: Dict, tokens, sp: Optional[str] = None,
             tp: Optional[str] = None, return_hidden: bool = False):
    """Token ids [B, L_local] -> logits [B, L_local, vocab].

    Inside ``shard_map``: ``sp`` names the sequence axis (tokens arrive
    sequence-sharded; ring attention, global positions), ``tp`` the
    tensor axis (params arrive head/feature-sharded via
    :func:`lm_param_specs`). Both None = dense single-device math.

    ``return_hidden`` stops after the final LayerNorm and returns
    [B, L_local, E] — for the fused losses (:func:`next_token_nll_fused`)
    that consume ``params["head"]`` directly and never materialize the
    [B, L, vocab] logits."""
    B, L = tokens.shape
    pos_offset = lax.axis_index(sp) * L if sp else 0
    x = params["embed"][tokens]
    x = x + lax.dynamic_slice_in_dim(params["pos"], pos_offset, L, 0)[None]

    for layer in params["layers"]:
        q, k, v = _project_qkv(layer, x, tp)
        scale = 1.0 / math.sqrt(q.shape[-1])
        if sp:
            attn = ring_attention(q, k, v, axis=sp, causal=True,
                                  scale=scale)
        else:
            attn = dot_product_attention(q, k, v, causal=True, scale=scale)
        x = _attn_out_residual(layer, attn, x, tp)
        x = _ffn_residual(layer, x, tp)

    if return_hidden:
        return _final_hidden(params, x)
    return _logits(params, x)


def lm_prefill(params: Dict, prompt, tp: Optional[str] = None):
    """Full forward over the prompt, capturing each layer's K/V into
    fixed-size [B, Lmax, H, D] caches (Lmax = the position table).

    The cache-plumbing half of :func:`lm_decode`, public so serving
    paths (:mod:`horovod_tpu.serve`) and tests can compose it with
    :func:`lm_decode_step` directly. Returns ``(caches, logits_last)``:
    per-layer ``{"k", "v"}`` dicts plus the last position's logits
    [B, vocab] — what the first generated token is sampled from."""
    B, Lp = prompt.shape
    Lmax = params["pos"].shape[0]
    x = params["embed"][prompt] + params["pos"][None, :Lp]
    caches = []
    for layer in params["layers"]:
        q, k, v = _project_qkv(layer, x, tp)
        scale = 1.0 / math.sqrt(q.shape[-1])
        pad = [(0, 0), (0, Lmax - Lp), (0, 0), (0, 0)]
        caches.append({"k": jnp.pad(k, pad), "v": jnp.pad(v, pad)})
        attn = dot_product_attention(q, k, v, causal=True, scale=scale)
        x = _attn_out_residual(layer, attn, x, tp)
        x = _ffn_residual(layer, x, tp)
    return caches, _logits(params, x[:, -1:])[:, 0]


def lm_decode_step(params: Dict, caches, tok, t, tp: Optional[str] = None):
    """One KV-cache decode step: write ``tok``'s K/V at position ``t``,
    attend the new token against the masked cache, return
    ``(new_caches, logits)`` with logits [B, vocab].

    ``tok`` is [B] int32, ``t`` a (traced or static) scalar absolute
    position; caches are :func:`lm_prefill`'s fixed-shape pytree, so the
    step traces into one static program regardless of position. The
    body of :func:`lm_decode`'s scan, public for serving paths."""
    x = params["embed"][tok][:, None] + \
        lax.dynamic_slice_in_dim(params["pos"], t, 1, 0)[None]
    new_caches = []
    for layer, cache in zip(params["layers"], caches):
        q, k, v = _project_qkv(layer, x, tp)              # [B, 1, H, D]
        ck = lax.dynamic_update_slice_in_dim(cache["k"], k, t, 1)
        cv = lax.dynamic_update_slice_in_dim(cache["v"], v, t, 1)
        new_caches.append({"k": ck, "v": cv})
        scale = 1.0 / math.sqrt(q.shape[-1])
        # The reference kernel with q_offset=t IS the cache mask
        # (k_pos <= t; unwritten slots masked), keeping decode-step
        # numerics identical to prefill/lm_apply.
        attn = dot_product_attention(q, ck, cv, causal=True,
                                     scale=scale, q_offset=t)
        x = _attn_out_residual(layer, attn, x, tp)
        x = _ffn_residual(layer, x, tp)
    return new_caches, _logits(params, x)[:, 0]


def lm_decode(params: Dict, prompt, steps: int, temperature: float = 0.0,
              rng=None, tp: Optional[str] = None):
    """Autoregressive generation with a static-shape KV cache.

    TPU-idiomatic decode (beyond the reference, which predates LM
    serving): the whole loop is ONE ``lax.scan`` — per-layer K/V caches
    of fixed [B, Lmax, H, D] shape live in the carry and are written with
    ``dynamic_update_slice``, each step attends the new token against the
    masked cache, so the program compiles once regardless of prompt or
    generation length. ``temperature=0`` is greedy argmax; otherwise
    categorical sampling with ``rng``. Composes with tp (head-sharded
    params inside shard_map; decode is forward-only). Returns the
    generated ids [B, steps].

    Built from the public cache plumbing — :func:`lm_prefill` then a
    scanned :func:`lm_decode_step` — which the continuous-batching
    serving engine (:mod:`horovod_tpu.serve`) reuses with a paged cache
    layout; the greedy engine output is pinned token-exact against this
    function."""
    B, Lp = prompt.shape
    Lmax = params["pos"].shape[0]
    if Lp + steps > Lmax:
        raise ValueError(
            f"prompt ({Lp}) + steps ({steps}) exceeds the position table "
            f"({Lmax})")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 requires an rng key")

    caches, logits_last = lm_prefill(params, prompt, tp)

    def pick(logits, key):
        if temperature > 0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, i):
        caches, logits, key = carry
        key, sub = (jax.random.split(key) if key is not None
                    else (None, None))
        tok = pick(logits.astype(jnp.float32), sub)       # [B]
        t = Lp + i                                        # absolute position
        new_caches, logits = lm_decode_step(params, caches, tok, t, tp)
        return (new_caches, logits, key), tok

    key0 = rng if temperature > 0 else None
    (_, _, _), toks = lax.scan(step, (caches, logits_last, key0),
                               jnp.arange(steps))
    return toks.T  # [B, steps]


def draft_params(params: Dict, layers: int) -> Dict:
    """Layer-skip self-draft: the target's FIRST ``layers`` transformer
    layers sharing the target's embed/pos/ln_f/head — the speculative-
    decoding draft model as a zero-copy VIEW of the target pytree
    (list slice of the layer dicts; no array is copied).

    Why a view instead of a second trained artifact: the draft's K/V
    for layer ``l < layers`` are computed by exactly the target's first
    ``l+1`` layers, so the draft shares the target's KV cache rows, the
    target's tp sharding (head/feature divisibility holds by
    construction), and the target's params-distribution path — the
    serving fleet's wire transports and ``update_params`` need no
    second weight artifact. The result plugs straight into
    :func:`lm_decode_step` / :func:`lm_prefill`."""
    n = len(params["layers"])
    if not 1 <= layers <= n:
        raise ValueError(
            f"draft_params: layers={layers} outside 1..{n} (the target "
            "has that many transformer layers)")
    return {"embed": params["embed"], "pos": params["pos"],
            "layers": params["layers"][:layers],
            "ln_f": params["ln_f"], "head": params["head"]}


def lm_verify_window(params: Dict, caches, toks, t,
                     tp: Optional[str] = None):
    """Speculative-decoding verify pass: ONE rectangular-causal step
    over a ``w``-token window — write the window's K/V rows at
    positions ``t..t+w-1`` and return the logits at ALL ``w``
    positions, so a draft's ``w-1`` proposals are verified by a single
    target dispatch instead of ``w`` sequential decode steps.

    ``toks`` is [B, w] int32 (row 0 = the last emitted token, rows
    1..w-1 = the draft's proposals), ``t`` the window's first absolute
    position; caches are :func:`lm_prefill`'s fixed-shape pytree.
    Returns ``(new_caches, logits [B, w, vocab])``.

    The attention is exactly the chunked-prefill shape — queries at
    global positions ``t..t+w-1`` over the full masked cache with
    ``q_offset=t, k_offset=0`` — so greedy argmaxes match ``w``
    sequential :func:`lm_decode_step` calls (masked softmax terms are
    exactly zero), and ``w=1`` IS :func:`lm_decode_step` shape-for-
    shape. Rows past an accepted prefix need no erasure: the next
    window overwrites positions it reaches and the causal mask hides
    positions beyond its own last query."""
    w = toks.shape[1]
    x = params["embed"][toks] + \
        lax.dynamic_slice_in_dim(params["pos"], t, w, 0)[None]
    new_caches = []
    for layer, cache in zip(params["layers"], caches):
        q, k, v = _project_qkv(layer, x, tp)              # [B, w, H, D]
        ck = lax.dynamic_update_slice_in_dim(cache["k"], k, t, 1)
        cv = lax.dynamic_update_slice_in_dim(cache["v"], v, t, 1)
        new_caches.append({"k": ck, "v": cv})
        scale = 1.0 / math.sqrt(q.shape[-1])
        attn = dot_product_attention(q, ck, cv, causal=True,
                                     scale=scale, q_offset=t)
        x = _attn_out_residual(layer, attn, x, tp)
        x = _ffn_residual(layer, x, tp)
    return new_caches, _logits(params, x)                 # [B, w, V]


def lm_decode_spec(params: Dict, prompt, steps: int, *, k: int,
                   draft_layers: int, tp: Optional[str] = None):
    """Greedy speculative decoding, the model-level reference the
    serving engine's spec path is pinned against: the layer-skip draft
    (:func:`draft_params`) proposes up to ``k`` tokens per tick, the
    target verifies all proposals plus one bonus position in a single
    :func:`lm_verify_window` pass, and the longest prefix where draft
    and target argmaxes agree is kept (plus the target's token at the
    first mismatch — the correction — or one bonus token when every
    proposal matched).

    Provably bit-identical to greedy :func:`lm_decode`: every emitted
    token is ``argmax(float32 target logits | emitted prefix)``
    regardless of WHAT the draft proposed or where tick boundaries
    fall — proposals only decide how many target argmaxes one dispatch
    yields. Returns the generated ids [1, steps] (single-row: the
    accept rule makes rows diverge in length)."""
    B, Lp = prompt.shape
    if B != 1:
        raise ValueError(
            f"lm_decode_spec is single-row (got B={B}): acceptance "
            "lengths diverge per row")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    Lmax = params["pos"].shape[0]
    if Lp + steps > Lmax:
        raise ValueError(
            f"prompt ({Lp}) + steps ({steps}) exceeds the position table "
            f"({Lmax})")
    dparams = draft_params(params, draft_layers)

    caches, logits_last = lm_prefill(params, prompt, tp)
    out = [int(jnp.argmax(logits_last.astype(jnp.float32), axis=-1)[0])]
    while len(out) < steps:
        t = Lp + len(out) - 1
        # Budget clamp: never verify past the generation budget (the
        # serving engine's page-grant bound is the same arithmetic).
        k_eff = min(k, steps - len(out) - 1)
        w = k_eff + 1
        # Draft proposals: k_eff sequential single-token steps over the
        # TARGET's first draft_layers caches (layer-skip shares rows);
        # the draft's writes land on a discarded branch of the pytree —
        # the verify pass below writes the rows that persist.
        dcaches = caches[:draft_layers]
        tok, d = out[-1], []
        for i in range(k_eff):
            dcaches, dlg = lm_decode_step(
                dparams, dcaches, jnp.full((1,), tok, jnp.int32),
                t + i, tp)
            tok = int(jnp.argmax(dlg.astype(jnp.float32), axis=-1)[0])
            d.append(tok)
        window = jnp.asarray([[out[-1]] + d], jnp.int32)      # [1, w]
        caches, vlg = lm_verify_window(params, caches, window, t, tp)
        tgt = jnp.argmax(vlg.astype(jnp.float32), axis=-1)[0]  # [w]
        for i in range(w):
            out.append(int(tgt[i]))
            if i < w - 1 and d[i] != int(tgt[i]):
                break   # correction emitted; rest of the window stale
    return jnp.asarray([out], jnp.int32)                  # [1, steps]


def stack_layers(params: Dict):
    """Split the param pytree for pipeline parallelism: the per-layer
    dicts stack into leading-axis arrays (shard with ``P(pp)`` so each
    stage chip holds one block), everything else stays replicated.
    Returns ``(rest, stacked_layers)``."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *params["layers"])
    rest = {k: v for k, v in params.items() if k != "layers"}
    return rest, stacked


def lm_pp_specs(rest: Dict, stacked):
    """Spec pytrees for :func:`lm_apply_pp` under shard_map: replicated
    ``rest``, stage-sharded layers."""
    from jax.sharding import PartitionSpec as P

    return (jax.tree_util.tree_map(lambda _: P(), rest),
            jax.tree_util.tree_map(lambda _: P("pp"), stacked))


def lm_apply_pp(rest: Dict, stacked_layers, tokens, axis: str = "pp",
                microbatches: int = 2, remat: bool = False):
    """Pipeline-parallel forward: one transformer block per stage chip
    (GPipe schedule over ``axis``, :mod:`horovod_tpu.parallel.pipeline`).

    ``stacked_layers`` leaves carry a leading [n_layers] axis sharded
    ``P(axis)`` — n_layers must equal the axis size. Embedding and head
    run replicated on every stage chip; the batch splits into
    ``microbatches``. Exactness (forward AND gradients, thanks to the
    exact-VJP pipeline sum) vs the flat :func:`lm_apply` is pinned in
    tests/test_parallel_lm.py."""
    from horovod_tpu.parallel.pipeline import pipeline_apply

    B, L = tokens.shape
    M = microbatches
    if B % M != 0:
        raise ValueError(
            f"lm_apply_pp: batch {B} must divide into microbatches={M} "
            f"(each stage tick processes one microbatch of B/M sequences)")
    leaves = jax.tree_util.tree_leaves(stacked_layers)
    n_stages = lax.axis_size(axis)
    if leaves and leaves[0].shape[0] != 1:
        # Inside shard_map with P(axis) on the stack, the per-chip view
        # keeps a length-1 leading stage axis (n_layers == axis size).
        # Anything else — a mis-sized stack, or a full stack passed
        # replicated without the P(axis) in_spec — would surface as a
        # cryptic reshape/einsum error deep inside pipeline_apply.
        raise ValueError(
            f"lm_apply_pp: per-chip stacked_layers leading dim is "
            f"{leaves[0].shape[0]}, expected 1 — pass n_layers == "
            f"'{axis}' axis size ({n_stages}) blocks sharded with "
            f"P('{axis}') (one transformer block per stage chip)")
    x = rest["embed"][tokens] + rest["pos"][None, :L]
    xm = x.reshape(M, B // M, L, x.shape[-1])

    def stage(layer, a):
        q, k, v = _project_qkv(layer, a, None)
        scale = 1.0 / math.sqrt(q.shape[-1])
        attn = dot_product_attention(q, k, v, causal=True, scale=scale)
        a = _attn_out_residual(layer, attn, a, None)
        return _ffn_residual(layer, a, None)

    out = pipeline_apply(stage, stacked_layers, xm, axis, remat=remat)
    return _logits(rest, out.reshape(B, L, x.shape[-1]))


def init_moe_lm_params(rng, vocab: int, max_len: int, layers: int,
                       heads: int, head_dim: int, ffn: int,
                       num_experts: int, dtype=jnp.float32) -> Dict:
    """Switch-MoE variant of :func:`init_lm_params`: each block's dense
    MLP becomes a router (``gate`` [E_dim, experts], replicated) plus
    ``experts`` stacked expert MLPs (leading axis ``num_experts`` —
    shard ``P(ep)`` so each chip holds E/P experts)."""
    params = init_lm_params(rng, vocab, max_len, layers, heads, head_dim,
                            ffn, dtype)
    embed_dim = heads * head_dim
    for i, layer in enumerate(params["layers"]):
        k = jax.random.fold_in(jax.random.fold_in(rng, 1000), i)
        kg, ku, kd = jax.random.split(k, 3)
        for key in ("wup", "bup", "wdn", "bdn"):
            del layer[key]
        layer["gate"] = (jax.random.normal(kg, (embed_dim, num_experts))
                         / math.sqrt(embed_dim)).astype(dtype)
        layer["experts"] = {
            "up": (jax.random.normal(ku, (num_experts, embed_dim, ffn))
                   / math.sqrt(embed_dim)).astype(dtype),
            "bup": jnp.zeros((num_experts, ffn), dtype),
            "dn": (jax.random.normal(kd, (num_experts, ffn, embed_dim))
                   / math.sqrt(ffn)).astype(dtype),
            "bdn": jnp.zeros((num_experts, embed_dim), dtype),
        }
    return params


def moe_lm_param_specs(layers: int, ep_axis: Optional[str]):
    """Spec pytree for :func:`lm_apply_moe` under shard_map: expert
    stacks shard their leading axis over ``ep_axis``, all else
    replicated."""
    from jax.sharding import PartitionSpec as P

    e = ep_axis
    layer_spec = {
        "ln1": {"g": P(), "b": P()},
        "wqkv": P(),
        "wo": P(),
        "bo": P(),
        "ln2": {"g": P(), "b": P()},
        "gate": P(),
        "experts": {"up": P(e), "bup": P(e), "dn": P(e), "bdn": P(e)},
    }
    return {
        "embed": P(),
        "pos": P(),
        "layers": [dict(layer_spec) for _ in range(layers)],
        "ln_f": {"g": P(), "b": P()},
        "head": P(),
    }


def _expert_mlp(p, tokens):
    h = jax.nn.gelu(tokens @ p["up"] + p["bup"])
    return h @ p["dn"] + p["bdn"]


def lm_apply_moe(params: Dict, tokens, ep: Optional[str] = None,
                 capacity_factor: float = 1.25):
    """Switch-MoE LM forward: tokens [B_local, L] -> (logits, aux_loss).

    Inside ``shard_map`` with ``ep`` set, the batch shards over the axis
    (data parallel for the dense parts) and each chip's experts process
    tokens routed to them by two all_to_alls
    (:func:`horovod_tpu.parallel.moe.moe_layer`). ``ep=None`` runs the
    identical routing math with every expert local — the dense reference
    the exactness tests compare against. ``aux_loss`` is the Switch
    load-balancing loss (mean over chips; add scaled to the main loss)."""
    from horovod_tpu.parallel.moe import moe_layer, top1_routing

    B, L = tokens.shape
    x = params["embed"][tokens] + params["pos"][None, :L]
    aux_total = 0.0
    for layer in params["layers"]:
        q, k, v = _project_qkv(layer, x, None)
        scale = 1.0 / math.sqrt(q.shape[-1])
        attn = dot_product_attention(q, k, v, causal=True, scale=scale)
        x = _attn_out_residual(layer, attn, x, None)

        m = _layernorm(x, layer["ln2"]["g"], layer["ln2"]["b"])
        flat = m.reshape(B * L, m.shape[-1])
        if ep:
            y, aux = moe_layer(flat, layer["gate"], _expert_mlp,
                               layer["experts"], axis=ep,
                               capacity_factor=capacity_factor,
                               return_aux=True)
        else:
            num_experts = layer["experts"]["up"].shape[0]
            T = flat.shape[0]
            capacity = max(1, math.ceil(T * capacity_factor / num_experts))
            dispatch, combine, aux = top1_routing(
                flat, layer["gate"], num_experts, capacity)
            slots = jnp.einsum("tec,td->ecd", dispatch,
                               flat.astype(jnp.float32))
            out = jax.vmap(_expert_mlp)(layer["experts"],
                                        slots.astype(flat.dtype))
            y = jnp.einsum("tec,ecd->td", combine,
                           out.astype(jnp.float32)).astype(flat.dtype)
        x = x + y.reshape(B, L, -1)
        aux_total = aux_total + aux

    return _logits(params, x), aux_total / len(params["layers"])


def moe_reduce_grads(grads: Dict, axis: str = "ep"):
    """Gradient reduction for :func:`lm_apply_moe`.

    Loss contract: the caller differentiates the PER-CHIP mean nll over
    its token shard (no collective inside the loss — rank-varying), and
    the global objective is the mean of those terms. Then:

    * replicated leaves (embed, attention, gates, head): MEAN over the
      axis (vma-aware: typed grads arrive as the auto-summed total and
      only need the /n);
    * expert shards: the all_to_all backward already returned every
      chip's contribution to this chip's experts, so the grad is the
      data-complete SUM — divide by the axis size (NO collective: a
      pmean/psum would mix gradients of *different* experts)."""
    from horovod_tpu.parallel._vma import (
        reduce_cotangent,
        scale_sharded_cotangent,
    )

    out = {k: jax.tree_util.tree_map(
               lambda g: reduce_cotangent(g, axis, mean=True), v)
           for k, v in grads.items() if k != "layers"}
    out["layers"] = []
    for layer_g in grads["layers"]:
        red = {k: jax.tree_util.tree_map(
                   lambda g: reduce_cotangent(g, axis, mean=True), v)
               for k, v in layer_g.items() if k != "experts"}
        red["experts"] = jax.tree_util.tree_map(
            lambda g: scale_sharded_cotangent(g, axis),
            layer_g["experts"])
        out["layers"].append(red)
    return out


def pp_reduce_rest_grads(g_rest: Dict, axis: str = "pp"):
    """Gradient reduction for :func:`lm_apply_pp`'s replicated params.

    The embedding/positional tables are consumed only through stage 0's
    injection, so their per-chip grads are partial (full on the stage-0
    chip, zero elsewhere) — SUM over the axis. The final layernorm and
    head run replicated on the pipeline's broadcast output, so their
    grads are already full and identical on every chip — left untouched.
    Applied to grad values (never differentiated through)."""
    from horovod_tpu.parallel._vma import reduce_cotangent

    out = dict(g_rest)
    out["embed"] = reduce_cotangent(g_rest["embed"], axis, mean=False,
                                    invariant_loss=True)
    out["pos"] = reduce_cotangent(g_rest["pos"], axis, mean=False,
                                  invariant_loss=True)
    return out


def _shifted_targets(tokens, sp: Optional[str]):
    """Next-token targets + validity weights, sequence-shard aware.

    With ``sp``, each shard's last position needs the NEXT shard's first
    token as its target — one ppermute — and the final global position
    is masked out. Returns (targets [B, L], valid [B, L] fp32)."""
    B, L = tokens.shape
    if sp:
        n = lax.axis_size(sp)
        nxt = lax.ppermute(tokens[:, :1], sp,
                           [(i, (i - 1) % n) for i in range(n)])
        tgt = jnp.concatenate([tokens[:, 1:], nxt], axis=1)
        gpos = lax.axis_index(sp) * L + jnp.arange(L)
        valid = (gpos < n * L - 1).astype(jnp.float32)[None, :]
    else:
        tgt = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        valid = (jnp.arange(L) < L - 1).astype(jnp.float32)[None, :]
    return tgt, jnp.broadcast_to(valid, tokens.shape)


def next_token_nll(logits, tokens, sp: Optional[str] = None):
    """Mean next-token negative log-likelihood, sequence-shard aware
    (:func:`_shifted_targets`); the mean is taken over the sp axis so
    every chip returns the same global value. Matches the dense shift
    exactly."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt, valid = _shifted_targets(tokens, sp)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll * valid)
    local_cnt = jnp.sum(valid)
    if sp:
        # sum_across, not bare psum: gradients through a raw psum get
        # scaled by the axis size (see parallel/tp.py tp_region_output).
        return sum_across(local_sum, sp) / lax.psum(local_cnt, sp)
    return local_sum / local_cnt


def next_token_nll_fused(params: Dict, hidden, tokens,
                         sp: Optional[str] = None,
                         tp: Optional[str] = None,
                         vocab_parallel: bool = False,
                         t_chunk: int = 512):
    """:func:`next_token_nll` without the [B, L, vocab] logits tensor.

    ``hidden`` is :func:`lm_apply`'s ``return_hidden=True`` output; the
    vocab projection happens inside the chunked fused loss
    (ops/xent.py), so the step's largest HBM tensor never materializes.
    With ``vocab_parallel`` the head arrives [E, V/tp]-sharded
    (:func:`lm_param_specs` ``vocab_parallel=True``) and the Megatron-
    style variant assembles the normalizer over ``tp``. Exactly equal
    to logits-then-:func:`next_token_nll` (tests/test_parallel_lm.py).
    """
    from horovod_tpu.ops.xent import (fused_cross_entropy,
                                      tp_vocab_cross_entropy)

    B, L = tokens.shape
    tgt, valid = _shifted_targets(tokens, sp)
    e = hidden.shape[-1]
    h2 = hidden.reshape(B * L, e)
    t2 = tgt.reshape(B * L)
    w2 = valid.reshape(B * L)
    cnt = jnp.sum(w2)
    denom = lax.psum(cnt, sp) if sp else cnt
    if vocab_parallel:
        if not tp:
            raise ValueError("vocab_parallel needs a tp axis")
        local = tp_vocab_cross_entropy(h2, params["head"], t2, tp,
                                       t_chunk, weights=w2, denom=denom)
    else:
        local = fused_cross_entropy(h2, params["head"], t2, t_chunk,
                                    weights=w2, denom=denom)
    # Each sp shard contributes its own tokens' share of the globally-
    # normalized sum; sum_across (not bare psum) keeps the backward
    # unscaled, as in next_token_nll.
    return sum_across(local, sp) if sp else local


def reduce_grads(grads, dp: Optional[str] = None, sp: Optional[str] = None):
    """The gradient reduction that pairs with :func:`next_token_nll`.

    * ``sp``: SUM — the loss value is already normalized by the
      sp-global token count (psum inside the nll), so each sp rank's
      backward holds only its own tokens' contribution of the full
      gradient;
    * ``dp``: MEAN — the global loss is the mean of per-dp-shard means;
    * ``tp``: nothing — tp peers see identical data, so replicated
      leaves get identical grads and sharded leaves' grads are exactly
      their slice.

    Uniform over every leaf, replicated and tp-sharded alike."""
    from horovod_tpu.parallel._vma import reduce_cotangent

    if sp:
        # next_token_nll's sum_across makes the loss sp-invariant.
        grads = jax.tree_util.tree_map(
            lambda g: reduce_cotangent(g, sp, mean=False,
                                       invariant_loss=True), grads)
    if dp:
        grads = jax.tree_util.tree_map(
            lambda g: reduce_cotangent(g, dp, mean=True), grads)
    return grads
