"""Torch collective ops over the native core: sync + async + in-place
variants with autograd support.

Parity surface of reference horovod/torch/mpi_ops.py (438 LoC: v1/v2
dispatch, _handle_map keep-alive, autograd Function classes, poll/
synchronize). The execution engine differs by design: instead of one
pybind symbol per (dtype x op) enqueueing into the MPI coordinator
(reference torch/mpi_ops_v2.cc:236-339), tensors are viewed as numpy
buffers and enqueued into the TCP-ring native core (csrc/coordinator.cc);
torch-on-TPU traffic belongs to the XLA lane, so this binding's job is the
CPU eager lane.
"""

from __future__ import annotations

import re
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np
import torch

from horovod_tpu.native import NativeCore, NativeError

try:
    import ml_dtypes

    _BF16_NP = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16_NP = None

# Module-global core, bound by horovod_tpu.torch.init().
_core: Optional[NativeCore] = None

_name_regex = re.compile(r"[^a-zA-Z0-9_.]")
_name_lock = threading.Lock()
_name_counter = 0

# handle -> (keep-alive objects, completion callback -> result tensor).
# Mirrors the reference's _handle_map (torch/mpi_ops.py:51-54): arrays must
# outlive the background thread's pointer writes.
_handle_map: Dict[int, Tuple[Any, Any]] = {}
_handle_lock = threading.Lock()


def _set_core(core: Optional[NativeCore]) -> None:
    global _core
    _core = core


def _require_core() -> NativeCore:
    if _core is None:
        raise RuntimeError(
            "horovod_tpu.torch has not been initialized; call hvd.init().")
    return _core


def _next_name(op: str, name: Optional[str]) -> str:
    global _name_counter
    if name is not None:
        return _name_regex.sub("_", name)
    with _name_lock:
        _name_counter += 1
        return f"{op}.noname.{_name_counter}"


def _as_numpy(tensor: torch.Tensor) -> np.ndarray:
    """Zero-copy numpy view of a contiguous CPU tensor."""
    if tensor.dtype == torch.bfloat16:
        if _BF16_NP is None:
            raise TypeError("bfloat16 requires ml_dtypes")
        return tensor.view(torch.int16).numpy().view(_BF16_NP)
    return tensor.numpy()


def _prepare_inplace(tensor: torch.Tensor):
    """Returns (buffer tensor, copy_back needed). Non-contiguous tensors
    stage through a contiguous clone."""
    if not tensor.is_contiguous():
        return tensor.contiguous(), True
    return tensor, False


def _register(handle: int, keep: Any, complete) -> int:
    with _handle_lock:
        _handle_map[handle] = (keep, complete)
    return handle


# ---------------------------------------------------------------- allreduce


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None) -> int:
    """In-place asynchronous allreduce; returns a handle for
    poll/synchronize (reference mpi_ops.py:156-199)."""
    core = _require_core()
    if average and not tensor.is_floating_point():
        # In-place true division on an integral dtype raises an opaque
        # torch error at completion time; fail up front with guidance
        # (the reference documents average as float-only semantics).
        raise ValueError(
            f"allreduce with average=True is not supported for integer "
            f"tensor dtype {tensor.dtype}; pass average=False (sum) or "
            f"cast to a floating dtype first.")
    buf, copy_back = _prepare_inplace(tensor)
    arr = _as_numpy(buf)
    h = core.allreduce_async_(_next_name("allreduce", name), arr)

    def complete() -> torch.Tensor:
        if copy_back:
            tensor.copy_(buf)
        if average:
            tensor.div_(core.size())
        return tensor

    return _register(h, (tensor, buf, arr), complete)


def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None) -> int:
    """Out-of-place asynchronous allreduce."""
    output = tensor.detach().clone()
    return allreduce_async_(output, average, name)


class _HorovodAllreduce(torch.autograd.Function):
    """Allreduce with gradient = allreduce (reference mpi_ops.py:110-121;
    the transpose of a sum over ranks is a sum over ranks)."""

    @staticmethod
    def forward(ctx, tensor, average, name):
        ctx.average = average
        output = tensor.detach().clone()
        h = allreduce_async_(output, average, name)
        return synchronize(h)

    @staticmethod
    def backward(ctx, grad_output):
        # Clone: the reduce is in-place and the incoming gradient buffer
        # may be user-supplied or shared with the graph.
        h = allreduce_async_(grad_output.detach().clone().contiguous(),
                             ctx.average)
        return synchronize(h), None, None


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None, compression=None):
    """Synchronous out-of-place allreduce, differentiable."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    summed = _HorovodAllreduce.apply(compressed, average, name)
    return compression.decompress(summed, ctx)


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None) -> torch.Tensor:
    """Synchronous in-place allreduce (reference mpi_ops.py:201-219)."""
    return synchronize(allreduce_async_(tensor, average, name))


# ---------------------------------------------------------------- allgather


def allgather_async(tensor: torch.Tensor,
                    name: Optional[str] = None) -> int:
    """Asynchronous allgather: concatenation along dim 0 across ranks
    (reference mpi_ops.py:256-281)."""
    core = _require_core()
    buf = tensor if tensor.is_contiguous() else tensor.contiguous()
    arr = _as_numpy(buf)
    h = core.allgather_async(_next_name("allgather", name), arr)
    trailing = tuple(tensor.shape[1:])
    dtype = tensor.dtype

    def complete() -> torch.Tensor:
        out_np = core.take_result(h, arr.dtype, trailing)
        if dtype == torch.bfloat16:
            out = torch.from_numpy(out_np.view(np.int16)).view(torch.bfloat16)
        else:
            out = torch.from_numpy(out_np)
        return out

    return _register(h, (tensor, buf, arr), complete)


class _HorovodAllgather(torch.autograd.Function):
    """Allgather with gradient = allreduce + slice of this rank's rows
    (reference mpi_ops.py:236-254, tensorflow/mpi_ops.py:127-148)."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.my_rows = tensor.shape[0] if tensor.dim() > 0 else 1
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        # Offsets need every rank's row count; gather them lazily here so
        # forward-only calls pay no extra collective (the reference also
        # defers this to the gradient, mpi_ops.py:236-254).
        rows = torch.tensor([ctx.my_rows], dtype=torch.int64)
        all_rows = synchronize(allgather_async(rows))
        rank = _require_core().rank()
        offset = int(all_rows[:rank].sum())
        summed = synchronize(allreduce_async_(
            grad_output.detach().clone().contiguous(), average=False))
        return summed[offset:offset + ctx.my_rows], None


def allgather(tensor: torch.Tensor, name: Optional[str] = None):
    """Synchronous allgather, differentiable."""
    return _HorovodAllgather.apply(tensor, name)


# ---------------------------------------------------------------- broadcast


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    """In-place asynchronous broadcast (reference mpi_ops.py:361-380)."""
    core = _require_core()
    buf, copy_back = _prepare_inplace(tensor)
    arr = _as_numpy(buf)
    h = core.broadcast_async_(_next_name("broadcast", name), arr, root_rank)

    def complete() -> torch.Tensor:
        if copy_back:
            tensor.copy_(buf)
        return tensor

    return _register(h, (tensor, buf, arr), complete)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    output = tensor.detach().clone()
    return broadcast_async_(output, root_rank, name)


class _HorovodBroadcast(torch.autograd.Function):
    """Broadcast with gradient = allreduce on root, zero elsewhere
    (reference mpi_ops.py:318-332, tensorflow/mpi_ops.py:168-183)."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        output = tensor.detach().clone()
        return synchronize(broadcast_async_(output, root_rank, name))

    @staticmethod
    def backward(ctx, grad_output):
        summed = synchronize(allreduce_async_(
            grad_output.detach().clone().contiguous(), average=False))
        if _require_core().rank() != ctx.root_rank:
            summed = torch.zeros_like(summed)
        return summed, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None):
    """Synchronous out-of-place broadcast, differentiable."""
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


# --------------------------------------------------------------- completion


def poll(handle: int) -> bool:
    """Non-blocking readiness check (reference mpi_ops.py:406-420)."""
    return _require_core().poll(handle)


def synchronize(handle: int):
    """Wait for an async op; returns its result tensor
    (reference mpi_ops.py:422-438)."""
    core = _require_core()
    with _handle_lock:
        entry = _handle_map.pop(handle, None)
    if entry is None:
        raise ValueError(f"unknown handle {handle}")
    _, complete = entry
    core.wait(handle)
    result = complete()
    core.release(handle)
    return result
